"""Kill/resume parity worker for ``mx.train.ElasticTrainer``.

Three modes driven by ``tests/test_elastic_train.py``:

* ``straight`` — train ``--steps`` steps uninterrupted, dump final
  weights (+ update counter) to ``--out``.
* ``crash`` — train ``--kill-at`` steps, checkpoint (async daemon +
  explicit flush, so the commit is durable), then die by SIGKILL —
  the hard-preemption case: no atexit, no flushes, no goodbyes.
* ``resume`` — rebuild the identical program, restore the latest
  checkpoint (parameters, optimizer state, update counter, lr
  schedule, RNG streams, data-iterator position) and train the
  remaining steps; dump final weights.

``straight`` and ``crash``+``resume`` must produce bit-identical
weights: the model has Dropout (consumes the PRNG stream every step),
the loader is shuffled (position + shuffle seed must survive), the
optimizer is adam with a FactorScheduler (slots + num_update + lr
state must survive).
"""

import argparse
import os
import signal
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import _cpu_guard  # noqa: E402
_cpu_guard.force_cpu()

import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, parallel  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.train import ElasticTrainer  # noqa: E402


def build(ckpt_dir):
    mx.random.seed(11)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=4, activation='relu'))
    net.add(nn.Dropout(0.5))
    net.add(nn.Dense(2))
    net.initialize()

    rng = onp.random.default_rng(0)
    X = rng.standard_normal((32, 4)).astype('float32')
    Y = rng.standard_normal((32, 2)).astype('float32')
    loader = gluon.data.DataLoader(gluon.data.ArrayDataset(X, Y),
                                   batch_size=8, shuffle=True)
    it = loader.resumable(shuffle_seed=5)

    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.7,
                                            base_lr=0.01)
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': 0.01, 'lr_scheduler': sched})
    mgr = parallel.SharedCheckpointManager(ckpt_dir, max_to_keep=2)
    et = ElasticTrainer(dict(net.collect_params()), trainer, mgr,
                        data_iter=it, name='parity')
    return net, trainer, it, et


def train_step(net, trainer, it):
    x, y = next(it)
    with autograd.record():
        out = net(x)
        loss = ((out - y) ** 2).mean()
    loss.backward()
    trainer.step(1)


def dump(path, net, trainer):
    arrs = {k: v.data().asnumpy() for k, v in net.collect_params().items()}
    arrs['num_update'] = onp.array(trainer._optimizer.num_update)
    onp.savez(path, **arrs)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--mode', choices=('straight', 'crash', 'resume'),
                    required=True)
    ap.add_argument('--ckpt-dir', required=True)
    ap.add_argument('--out', required=True)
    ap.add_argument('--steps', type=int, default=6)
    ap.add_argument('--kill-at', type=int, default=3)
    args = ap.parse_args()

    net, trainer, it, et = build(args.ckpt_dir)

    if args.mode == 'straight':
        for _ in range(args.steps):
            train_step(net, trainer, it)
        dump(args.out, net, trainer)
        print(f'straight: {args.steps} steps done')
        return

    if args.mode == 'crash':
        for _ in range(args.kill_at):
            train_step(net, trainer, it)
        et.save(args.kill_at - 1, block=True)
        assert et.flush(timeout=60)
        print(f'crash: checkpoint at step {args.kill_at - 1} durable, '
              'dying now', flush=True)
        os.kill(os.getpid(), signal.SIGKILL)
        raise AssertionError('unreachable')

    # resume
    start = et.restore()
    assert start == args.kill_at - 1, start
    for _ in range(start + 1, args.steps):
        train_step(net, trainer, it)
    dump(args.out, net, trainer)
    print(f'resume: restored step {start}, trained to {args.steps}')


if __name__ == '__main__':
    main()
