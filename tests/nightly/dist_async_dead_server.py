"""dist_async dead-server drill (VERDICT r4 item 10).

Reference contract: ``include/mxnet/kvstore.h:408`` — after a node
stops heartbeating, ``get_num_dead_node`` must report it; surviving
workers touching the dead server must get a CLEAN error, never a hang.

Launched as::

    MXNET_KVSTORE_NUM_SERVERS=2 python tools/launch.py -n 4 \
        --launcher local python tests/nightly/dist_async_dead_server.py

Script: 4 workers / 2 servers (server s on rank s). Everyone trains a
few pushes; then rank 1 — which HOSTS server 1 — dies abruptly
(os._exit, no close(), so no 'bye' deregistration either). Survivors
assert:

* ``get_num_dead_node`` counts the lost rank (stale heartbeat) plus
  the unreachable server;
* a push/pull routed to server 1's keys raises within the dial
  timeout — a clean ConnectionError/RuntimeError, not a hang;
* server 0's keys keep working: the PS degrades per-shard, matching
  the reference's per-server failure domain.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import _cpu_guard  # noqa: E402
_cpu_guard.force_cpu()

import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import kvstore  # noqa: E402


def main():
    kv = kvstore.create('dist_async')
    rank, size = kv.rank, kv.num_workers
    assert kv._nserv == 2

    # place one key on each server, verifiably
    kv.init('a', mx.np.zeros((4,)))
    kv.barrier()
    stats = kv.server_stats()
    by_server = {sid: list(keys) for sid, keys in stats.items()}
    assert 'a' in by_server[kv._key_server('a')]
    # find key names hashing to each server so the test is deterministic
    k0 = next(f'k{i}' for i in range(100) if kv._key_server(f'k{i}') == 0)
    k1 = next(f'k{i}' for i in range(100) if kv._key_server(f'k{i}') == 1)
    for k in (k0, k1):
        kv.init(k, mx.np.zeros((4,)))
    kv.barrier()
    for k in (k0, k1):
        kv.push(k, mx.np.ones((4,)))
    kv.barrier()
    want = float(size)
    for k in (k0, k1):
        onp.testing.assert_allclose(kv.pull(k).asnumpy(),
                                    onp.full((4,), want), rtol=1e-6)
    kv.barrier()

    if rank == 1:
        # the rank hosting server 1 dies NOW — no close(), no bye, the
        # socket just goes away (a real crash, not a clean departure)
        print(f'worker {rank}/{size}: dying with server 1', flush=True)
        sys.stdout.flush()
        os._exit(0)

    # survivors: wait out the heartbeat staleness window. dead >= 2
    # requires BOTH detection paths: the unreachable-server ping (counts
    # immediately) AND server 0's stale-heartbeat accounting for the
    # lost rank (include/mxnet/kvstore.h:408) — dead == 1 would mean
    # the heartbeat table is broken
    deadline = time.monotonic() + 30
    dead = 0
    while time.monotonic() < deadline:
        time.sleep(1.0)
        try:
            dead = kv.get_num_dead_node(timeout=3)
        except Exception:
            dead = -1     # server 0 must stay answerable
        if dead >= 2:
            break
    assert dead >= 2, f'rank {rank}: dead={dead} — expected the lost ' \
        f'worker heartbeat AND the unreachable server to be counted'

    # touching the dead server must FAIL CLEANLY within the dial window
    t0 = time.monotonic()
    try:
        kv.push(k1, mx.np.ones((4,)))
        raised = False
    except (ConnectionError, RuntimeError, OSError):
        raised = True
    elapsed = time.monotonic() - t0
    assert raised, f'rank {rank}: push to dead server did not error'
    assert elapsed < 60, f'rank {rank}: dead-server error took {elapsed}s'

    # server 0's shard keeps serving
    kv.push(k0, mx.np.ones((4,)))
    got = kv.pull(k0).asnumpy()
    assert got[0] >= want + 1.0, got

    print(f'worker {rank}/{size}: dead-server drill passed '
          f'(dead={dead}, error after {elapsed:.1f}s)', flush=True)


if __name__ == '__main__':
    main()
