"""Multi-process dist-kvstore worker script.

Reference: ``tests/nightly/dist_sync_kvstore.py`` — a plain worker script
asserting synchronous kvstore semantics, launched as a local multi-process
cluster by ``tools/launch.py -n N --launcher local`` (the reference's CI
pattern from ``tests/nightly/test_distributed_training-gpu.sh:27-34``,
scheduler+servers+workers collapsed here to N equal SPMD processes).

Run directly:
    JAX_PLATFORMS=cpu python tools/launch.py -n 2 --launcher local \
        python tests/nightly/dist_sync_kvstore.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import _cpu_guard  # noqa: E402  (axon sitecustomize overrides JAX_PLATFORMS)
_cpu_guard.force_cpu()

import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import kvstore, parallel  # noqa: E402


def main():
    parallel.init_distributed()
    kv = kvstore.create('dist_tpu_sync')
    rank, size = kv.rank, kv.num_workers
    assert size == int(os.environ.get('MX_NPROC', '1')), \
        (size, os.environ.get('MX_NPROC'))

    # --- synchronous pushpull: out == sum over workers (reference
    # dist_sync_kvstore.py check_default_keys)
    kv.init(3, mx.np.zeros((4, 2)))
    val = mx.np.array(onp.full((4, 2), rank + 1.0, 'f'))
    out = mx.np.zeros((4, 2))
    kv.pushpull(3, val, out=out)
    expect = sum(r + 1.0 for r in range(size))
    onp.testing.assert_allclose(out.asnumpy(), onp.full((4, 2), expect),
                                rtol=1e-6)

    # --- broadcast: rank 0's value is authoritative (KVStoreDist::Init)
    mine = mx.np.array(onp.full((3,), 100.0 + rank, 'f'))
    got = mx.np.zeros((3,))
    kv.broadcast('w0', mine, out=got)
    onp.testing.assert_allclose(got.asnumpy(), onp.full((3,), 100.0),
                                rtol=1e-6)

    # --- barrier then compressed pushpull (2-bit, error feedback kept
    # worker-local; each worker contributes ±threshold after quantization)
    kv.barrier()
    kv.set_gradient_compression({'type': '2bit', 'threshold': 0.5})
    g = mx.np.array(onp.array([0.6, -0.7, 0.1, 0.0], 'f'))
    cout = mx.np.zeros((4,))
    kv.pushpull(7, g, out=cout)
    onp.testing.assert_allclose(
        cout.asnumpy(), [0.5 * size, -0.5 * size, 0.0, 0.0], atol=1e-6)

    # --- optimizer-on-store: the reference's update_on_kvstore runs the
    # optimizer on the PS (kvstore_dist_server.h ApplyUpdates); here the
    # updater applies to every host's replica of the store after the
    # global allreduce, so all ranks converge identically.
    kv2 = kvstore.create('dist_tpu_sync')
    kv2.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    kv2.init(0, mx.np.array(onp.full((2,), 10.0, 'f')))
    grad = mx.np.array(onp.full((2,), 1.0, 'f'))
    wout = mx.np.zeros((2,))
    kv2.pushpull(0, grad, out=wout)
    # merged grad = size * 1.0; w <- 10 - 0.5 * size
    onp.testing.assert_allclose(wout.asnumpy(),
                                onp.full((2,), 10.0 - 0.5 * size),
                                rtol=1e-6)

    # --- fused (bucketed) pushpull: many keys, one collective per fusion
    # buffer (reference PushPullDefault + P3 slicing, here XLA psum)
    fkeys = list(range(20, 27))
    fvals = [mx.np.array(onp.full((5, 3), (rank + 1.0) * (k - 19), 'f'))
             for k in fkeys]
    fouts = [mx.np.zeros((5, 3)) for _ in fkeys]
    kv.set_gradient_compression({'type': 'none'})
    kv.fused_pushpull(fkeys, fvals, outs=[[o] for o in fouts],
                      priorities=[-i for i in range(len(fkeys))])
    for k, o in zip(fkeys, fouts):
        want = sum((r + 1.0) * (k - 19) for r in range(size))
        onp.testing.assert_allclose(o.asnumpy(), onp.full((5, 3), want),
                                    rtol=1e-6)

    # --- fused + 2-bit compression: words cross the wire, decode+sum on
    # device; each worker contributes +-threshold after quantization
    kvc = kvstore.create('dist_tpu_sync')
    kvc.set_gradient_compression({'type': '2bit', 'threshold': 0.5})
    cg = [mx.np.array(onp.array([0.6, -0.7, 0.1, 0.0], 'f')),
          mx.np.array(onp.array([[0.9, -0.1], [0.0, 0.55]], 'f'))]
    couts = [mx.np.zeros((4,)), mx.np.zeros((2, 2))]
    kvc.fused_pushpull([70, 71], cg, outs=couts)
    onp.testing.assert_allclose(
        couts[0].asnumpy(), [0.5 * size, -0.5 * size, 0.0, 0.0], atol=1e-6)
    onp.testing.assert_allclose(
        couts[1].asnumpy(),
        [[0.5 * size, 0.0], [0.0, 0.5 * size]], atol=1e-6)

    # --- ZeRO-1 sharded optimizer-on-store: updater runs once per key
    # globally (on its owner), weights all_gather back; every rank must
    # see identical post-update weights
    kvz = kvstore.create('dist_tpu_sync')
    kvz.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    zkeys = [0, 1, 2]
    for k in zkeys:
        kvz.init(k, mx.np.array(onp.full((3,), 10.0 * (k + 1), 'f')))
    zgrads = [mx.np.array(onp.full((3,), 1.0 * (k + 1), 'f'))
              for k in zkeys]
    zouts = [mx.np.zeros((3,)) for _ in zkeys]
    kvz.fused_pushpull(zkeys, zgrads, outs=zouts)
    for k, o in zip(zkeys, zouts):
        # merged grad = size*(k+1); w <- 10(k+1) - 0.5*size*(k+1)
        want = 10.0 * (k + 1) - 0.5 * size * (k + 1)
        onp.testing.assert_allclose(o.asnumpy(), onp.full((3,), want),
                                    rtol=1e-6)

    # --- row_sparse_pull across processes: store holds the full (dense)
    # table, each rank pulls its own row ids (reference PullRowSparse)
    kv.init('emb', mx.np.array(
        onp.arange(8, dtype='float32').reshape(4, 2)))
    rows = mx.np.array(onp.array([rank, 3]))
    pulled = kv.row_sparse_pull('emb', row_ids=rows)
    got = pulled.asnumpy()
    onp.testing.assert_allclose(got[rank], [2.0 * rank, 2.0 * rank + 1])
    onp.testing.assert_allclose(got[3], [6.0, 7.0])

    print(f'worker {rank}/{size}: all dist kvstore assertions passed',
          flush=True)


if __name__ == '__main__':
    main()
