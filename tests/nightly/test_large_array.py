"""Large (INT64-indexed) tensor support.

Reference: tests/nightly/test_large_array.py (1,757 LoC, 165 check
functions over LARGE_X x SMALL_Y tensors) + test_large_vector.py —
tensors beyond 2**32 elements, gated out of CI by runtime cost (the
reference runs them nightly; CMake flag USE_INT64_TENSOR_SIZE).

Here the same op families run at two scales:

* CI scale (default): LARGE_X=100_000 — every check always runs, so the
  int64-clean size/stride arithmetic and the index-dtype contracts stay
  covered per-commit;
* nightly scale: MXNET_TEST_LARGE_TENSOR=1 lifts LARGE_X to the
  reference's 100,000,000 rows (~20 GB host RAM) and enables the
  >2**32-element cases; jax x64 mode is switched on so index-producing
  ops (argmax/argsort/topk) can address past INT32_MAX — the runtime
  analog of the reference's USE_INT64_TENSOR_SIZE build flag.

Assertions follow the VERDICT guidance: shapes, index/output dtypes and
far-end element correctness — never speed.
"""

import os

import numpy as onp
import pytest

LARGE = os.environ.get('MXNET_TEST_LARGE_TENSOR', '') == '1'
if LARGE:
    import jax
    jax.config.update('jax_enable_x64', True)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

# reference LARGE_X = 100_000_000 rows x SMALL_Y = 50 cols
LARGE_X = 100_000_000 if LARGE else 100_000
SMALL_Y = 50
# index dtype an index-producing op must use at this scale
IDX_DT = onp.int64 if LARGE else onp.int32

largeonly = pytest.mark.skipif(
    not LARGE, reason='set MXNET_TEST_LARGE_TENSOR=1 '
    '(needs ~60 GB RAM headroom, nightly-scale)')


@pytest.fixture(autouse=True)
def _release_device_memory():
    """LARGE mode only: drop jax's executable/constant caches between
    tests — compiled executables can pin multi-GB baked constants, and
    at 20 GB per live array the suite has no slack for cache growth."""
    yield
    if LARGE:
        import gc
        import jax
        gc.collect()
        jax.clear_caches()


def _big(val=1.0, dtype='float32'):
    return mx.np.full((LARGE_X, SMALL_Y), val, dtype=dtype)


def _rows():
    """(LARGE_X, 1) row-index column, values 0..LARGE_X-1 in a float
    type wide enough to hold them exactly at the current scale."""
    return mx.np.arange(LARGE_X, dtype='float64' if LARGE
                        else 'float32').reshape(LARGE_X, 1)


# ------------------------------------------------------------ size/index
def test_int64_size_arithmetic():
    """Sizes/strides must be int64-clean even when the array itself is
    modest — the reference guards this with USE_INT64_TENSOR_SIZE."""
    a = mx.np.zeros((LARGE_X, SMALL_Y))
    assert a.size == LARGE_X * SMALL_Y
    assert a.shape == (LARGE_X, SMALL_Y)
    a[LARGE_X - 1, SMALL_Y - 1] = 3.0
    assert float(a[LARGE_X - 1, SMALL_Y - 1].asnumpy()) == 3.0


@largeonly
def test_beyond_int32_elements():
    """> 2**32 elements end to end (reference test_large_vector.py)."""
    n = 2 ** 32 + 2
    a = mx.np.ones((n,), dtype='int8')
    assert a.size == n
    s = a[n - 2:].asnumpy()
    assert s.shape == (2,)


@largeonly
def test_beyond_int32_argmax_index():
    """argmax over a > 2**32-element axis must return an index that
    int32 cannot hold — the dtype contract the nightly exists for."""
    n = 2 ** 32 + 8
    a = mx.np.zeros((n,), dtype='int8')
    a[n - 3] = 1
    idx = mx.np.argmax(a)
    assert onp.dtype(idx.dtype) == onp.int64
    assert int(idx.asnumpy()) == n - 3


# ------------------------------------------------------------- creation
@pytest.mark.parametrize('maker,val', [
    ('zeros', 0.0), ('ones', 1.0)])
def test_creation(maker, val):
    a = getattr(mx.np, maker)((LARGE_X, SMALL_Y))
    assert a.shape == (LARGE_X, SMALL_Y)
    assert float(a[LARGE_X - 1, SMALL_Y - 1].asnumpy()) == val


def test_full_and_arange():
    a = mx.np.full((LARGE_X, SMALL_Y), 7.5)
    assert float(a[LARGE_X - 1, 0].asnumpy()) == 7.5
    r = mx.np.arange(LARGE_X)
    assert r.shape == (LARGE_X,)
    assert int(r[LARGE_X - 1].asnumpy()) == LARGE_X - 1


# ----------------------------------------------------------- elementwise
def test_binary_arith_broadcast():
    a = _big(2.0)
    b = mx.np.arange(SMALL_Y, dtype='float32')    # broadcast over rows
    # thunks, NOT values: at nightly scale each result is ~20 GB, and
    # materializing all eight at once OOM-killed the r5 LARGE run —
    # compute, check, release one at a time
    checks = {
        'add': (lambda: a + b, lambda x: 2.0 + x),
        'sub': (lambda: a - b, lambda x: 2.0 - x),
        'mul': (lambda: a * b, lambda x: 2.0 * x),
        'div': (lambda: a / (b + 1.0), lambda x: 2.0 / (x + 1.0)),
        'pow': (lambda: a ** 2, lambda x: 4.0),
        'mod': (lambda: mx.np.mod(a, 1.5), lambda x: 0.5),
        'maximum': (lambda: mx.np.maximum(a, b), lambda x: max(2.0, x)),
        'minimum': (lambda: mx.np.minimum(a, b), lambda x: min(2.0, x)),
    }
    j = SMALL_Y - 1
    for name, (make, ref) in checks.items():
        out = make()
        assert out.shape == (LARGE_X, SMALL_Y), name
        got = float(out[LARGE_X - 1, j].asnumpy())
        assert abs(got - ref(float(j))) < 1e-5, name
        del out


def test_inplace_arith():
    a = _big(1.0)
    a += 2.0
    a *= 3.0
    a -= 1.0
    a /= 2.0
    assert float(a[LARGE_X - 1, 0].asnumpy()) == 4.0


def test_unary_math_family():
    a = _big(0.5)
    for name in ['exp', 'log1p', 'sqrt', 'sin', 'cos', 'tan', 'arcsin',
                 'arccos', 'arctan', 'sinh', 'cosh', 'tanh', 'arcsinh',
                 'arctanh', 'abs', 'ceil', 'floor', 'rint', 'sign',
                 'square', 'cbrt', 'reciprocal', 'radians', 'degrees',
                 'expm1']:
        out = getattr(mx.np, name)(a)
        assert out.shape == (LARGE_X, SMALL_Y), name
        want = getattr(onp, name)(onp.float32(0.5))
        got = float(out[LARGE_X - 1, SMALL_Y - 1].asnumpy())
        assert abs(got - float(want)) < 1e-5, name


def test_clip_fix_far_end():
    a = _rows() * mx.np.ones((1, SMALL_Y))
    c = mx.np.clip(a, 10.0, 100.0)
    assert float(c[LARGE_X - 1, 0].asnumpy()) == 100.0
    assert float(c[0, 0].asnumpy()) == 10.0
    f = mx.np.fix(mx.np.array([-1.7, 1.7]))
    onp.testing.assert_allclose(f.asnumpy(), [-1.0, 1.0])


# ------------------------------------------------------- logical/compare
def test_comparison_family():
    a = _big(2.0)
    b = _big(3.0)
    for name, want in [('greater', 0.0), ('less', 1.0),
                       ('greater_equal', 0.0), ('less_equal', 1.0),
                       ('equal', 0.0), ('not_equal', 1.0)]:
        out = getattr(mx.np, name)(a, b)
        assert out.shape == (LARGE_X, SMALL_Y)
        assert float(out[LARGE_X - 1, 0].asnumpy()) == want, name


def test_logical_family():
    t = _big(1.0).astype('bool')
    f = _big(0.0).astype('bool')
    assert bool(mx.np.logical_and(t, f)[LARGE_X - 1, 0].asnumpy()) is False
    assert bool(mx.np.logical_or(t, f)[LARGE_X - 1, 0].asnumpy()) is True
    assert bool(mx.np.logical_xor(t, t)[LARGE_X - 1, 0].asnumpy()) is False
    assert bool(mx.np.logical_not(f)[LARGE_X - 1, 0].asnumpy()) is True


# ------------------------------------------------------------ reductions
def test_reductions_full_and_axis():
    a = _big(1.0)
    assert float(a.sum().asnumpy()) == LARGE_X * SMALL_Y
    assert float(a.mean().asnumpy()) == 1.0
    col = a.sum(axis=0)
    assert col.shape == (SMALL_Y,)
    assert float(col[0].asnumpy()) == LARGE_X
    row = a.sum(axis=1)
    assert row.shape == (LARGE_X,)
    assert float(row[LARGE_X - 1].asnumpy()) == SMALL_Y
    m = _rows() * mx.np.ones((1, SMALL_Y))
    assert float(m.max().asnumpy()) == LARGE_X - 1
    assert float(m.min().asnumpy()) == 0.0
    assert float(mx.np.prod(mx.np.ones((LARGE_X,))).asnumpy()) == 1.0


def test_norm_and_std():
    a = _big(2.0)
    n = mx.np.linalg.norm(a, axis=1)
    assert n.shape == (LARGE_X,)
    assert abs(float(n[LARGE_X - 1].asnumpy()) -
               2.0 * SMALL_Y ** 0.5) < 1e-4
    assert float(a.std().asnumpy()) == 0.0


# ------------------------------------------------------------ index ops
def test_argmax_argmin_dtype_and_value():
    x = mx.np.zeros((LARGE_X, SMALL_Y))
    x[LARGE_X - 1, 7] = 5.0
    flat_idx = mx.np.argmax(x)
    assert onp.dtype(flat_idx.dtype) == IDX_DT
    assert int(flat_idx.asnumpy()) == (LARGE_X - 1) * SMALL_Y + 7
    per_col = mx.np.argmax(x, axis=0)
    assert per_col.shape == (SMALL_Y,)
    assert int(per_col[7].asnumpy()) == LARGE_X - 1
    x[0, 3] = -5.0
    assert int(mx.np.argmin(x, axis=0)[3].asnumpy()) == 0


def test_argsort_topk_dtypes():
    # int32 values: exact at any scale — float32 rounds integers above
    # 2**24, which made far-end assertions fail at LARGE_X=1e8 (the
    # contract under test is the INDEX dtype, not the value dtype)
    v = mx.np.arange(LARGE_X, dtype='int32')
    s = mx.np.argsort(v)
    assert s.shape == (LARGE_X,)
    assert onp.dtype(s.dtype) == IDX_DT
    assert int(s[0].asnumpy()) == 0
    top = mx.npx.topk(v, k=3, dtype='int64')
    assert top.shape == (3,)
    assert int(top[0].asnumpy()) == LARGE_X - 1


def test_cumsum_far_end():
    v = mx.np.ones((LARGE_X,), dtype='float64' if LARGE else 'float32')
    c = mx.np.cumsum(v)
    assert c.shape == (LARGE_X,)
    assert float(c[LARGE_X - 1].asnumpy()) == LARGE_X


def test_take_and_gather():
    a = _rows() * mx.np.ones((1, SMALL_Y))
    idx = mx.np.array(onp.array([0, LARGE_X - 1], IDX_DT))
    t = mx.np.take(a, idx, axis=0)
    assert t.shape == (2, SMALL_Y)
    assert float(t[1, 0].asnumpy()) == LARGE_X - 1
    g = mx.npx.gather_nd(a, mx.np.array(
        onp.array([[LARGE_X - 1, 0]], IDX_DT)))
    assert float(g.asnumpy().ravel()[0]) == LARGE_X - 1


def test_boolean_mask_far_end():
    v = mx.np.zeros((LARGE_X,))
    v[LARGE_X - 1] = 2.0
    got = v[v > 1.0]
    assert got.shape == (1,)
    assert float(got.asnumpy()[0]) == 2.0


def test_one_hot_and_pick():
    ids = mx.np.array(onp.array([0, SMALL_Y - 1], IDX_DT))
    oh = mx.npx.one_hot(ids, SMALL_Y)
    assert oh.shape == (2, SMALL_Y)
    assert float(oh[1, SMALL_Y - 1].asnumpy()) == 1.0
    a = _rows() * mx.np.ones((1, SMALL_Y))
    p = mx.npx.pick(a, mx.np.zeros((LARGE_X,)), axis=1)
    assert p.shape == (LARGE_X,)
    assert float(p[LARGE_X - 1].asnumpy()) == LARGE_X - 1


# ------------------------------------------------------------- shape ops
def test_reshape_transpose_expand():
    a = _big(1.0)
    r = a.reshape(SMALL_Y, LARGE_X)
    assert r.shape == (SMALL_Y, LARGE_X)
    t = mx.np.transpose(a)
    assert t.shape == (SMALL_Y, LARGE_X)
    e = mx.np.expand_dims(a, 0)
    assert e.shape == (1, LARGE_X, SMALL_Y)
    assert mx.np.squeeze(e, 0).shape == (LARGE_X, SMALL_Y)


def test_concat_split_stack():
    a = mx.np.ones((LARGE_X, 4))
    b = mx.np.zeros((LARGE_X, 4))
    c = mx.np.concatenate([a, b], axis=1)
    assert c.shape == (LARGE_X, 8)
    assert float(c[LARGE_X - 1, 0].asnumpy()) == 1.0
    assert float(c[LARGE_X - 1, 7].asnumpy()) == 0.0
    parts = mx.np.split(c, 2, axis=1)
    assert parts[0].shape == (LARGE_X, 4)
    s = mx.np.stack([a, b], axis=0)
    assert s.shape == (2, LARGE_X, 4)


def test_tile_repeat_flip_roll():
    # int32: see test_argsort_topk_dtypes — f32 rounds ints > 2**24
    v = mx.np.arange(LARGE_X, dtype='int32')
    f = mx.np.flip(v, 0)
    assert int(f[0].asnumpy()) == LARGE_X - 1
    r = mx.np.roll(v, 1)
    assert int(r[0].asnumpy()) == LARGE_X - 1
    t = mx.np.tile(mx.np.ones((LARGE_X, 1)), (1, 3))
    assert t.shape == (LARGE_X, 3)
    rep = mx.np.repeat(mx.np.ones((LARGE_X, 1)), 2, axis=1)
    assert rep.shape == (LARGE_X, 2)


def test_slice_family():
    a = _rows() * mx.np.ones((1, SMALL_Y))
    s = a[LARGE_X - 5:, :3]
    assert s.shape == (5, 3)
    assert float(s[4, 0].asnumpy()) == LARGE_X - 1
    sa = mx.npx.slice_axis(a, axis=0, begin=LARGE_X - 2, end=LARGE_X)
    assert sa.shape == (2, SMALL_Y)


def test_where_select():
    a = _big(1.0)
    b = _big(2.0)
    cond = _big(0.0).astype('bool')
    w = mx.np.where(cond, a, b)
    assert float(w[LARGE_X - 1, 0].asnumpy()) == 2.0


# ----------------------------------------------------------------- dtype
@pytest.mark.parametrize('dt', ['float16', 'bfloat16', 'int8', 'uint8',
                                'int32'] +
                         (['float64', 'int64'] if LARGE else []))
def test_astype_roundtrip(dt):
    # 64-bit element dtypes need x64 mode, which the nightly-scale run
    # switches on; CI scale covers the 32-bit-and-below families
    a = mx.np.ones((LARGE_X, 2))
    c = a.astype(dt)
    assert str(c.dtype) == dt
    assert c.shape == (LARGE_X, 2)
    back = c.astype('float32')
    assert float(back[LARGE_X - 1, 1].asnumpy()) == 1.0


# ------------------------------------------------------------- linalg/nn
def test_dense_dot_large_rows():
    x = mx.np.ones((LARGE_X, SMALL_Y))
    w = mx.np.ones((SMALL_Y, 4)) * 0.5
    y = mx.np.dot(x, w)
    assert y.shape == (LARGE_X, 4)
    assert abs(float(y[LARGE_X - 1, 3].asnumpy()) - SMALL_Y * 0.5) < 1e-4


def test_fully_connected_op():
    x = mx.np.ones((LARGE_X, SMALL_Y))
    w = mx.np.ones((8, SMALL_Y)) * 0.1
    b = mx.np.zeros((8,))
    y = mx.npx.fully_connected(x, w, b, num_hidden=8)
    assert y.shape == (LARGE_X, 8)
    assert abs(float(y[LARGE_X - 1, 0].asnumpy()) - SMALL_Y * 0.1) < 1e-3


def test_activation_family():
    a = _big(-0.5)
    for act in ['relu', 'sigmoid', 'tanh', 'softrelu']:
        out = mx.npx.activation(a, act_type=act)
        assert out.shape == (LARGE_X, SMALL_Y), act
    lr = mx.npx.leaky_relu(a, slope=0.1)
    assert abs(float(lr[LARGE_X - 1, 0].asnumpy()) + 0.05) < 1e-6


def test_softmax_family():
    a = mx.np.mod(_rows(), 7.0) * mx.np.ones((1, 8))
    s = mx.npx.softmax(a.astype('float32'), axis=-1)
    assert abs(float(s.sum(axis=1)[LARGE_X - 1].asnumpy()) - 1.0) < 1e-5
    ls = mx.npx.log_softmax(a.astype('float32'), axis=-1)
    assert abs(float(mx.np.exp(ls).sum(axis=1)[0].asnumpy()) - 1.0) < 1e-5


def test_layer_norm_large_rows():
    x = mx.np.ones((LARGE_X, 1)) * \
        mx.np.arange(SMALL_Y, dtype='float32')
    g = mx.np.ones((SMALL_Y,))
    b = mx.np.zeros((SMALL_Y,))
    y = mx.npx.layer_norm(x, g, b, axis=-1)
    assert y.shape == (LARGE_X, SMALL_Y)
    last = y[LARGE_X - 1].asnumpy()
    assert abs(last.mean()) < 1e-4 and abs(last.std() - 1.0) < 1e-2


def test_embedding_large_vocab():
    """Embedding with a LARGE_X-row table: index dtype must address
    every row (reference check_embedding/check_gluon_embedding)."""
    table = gluon.nn.Embedding(LARGE_X, 4)
    table.initialize()
    ids = mx.np.array(onp.array([[0, LARGE_X - 1]], IDX_DT))
    out = table(ids)
    assert out.shape == (1, 2, 4)
    want = table.weight.data()[LARGE_X - 1].asnumpy()
    onp.testing.assert_allclose(out.asnumpy()[0, 1], want, rtol=1e-6)


def test_sequence_mask_long():
    x = mx.np.ones((4, LARGE_X // 10))            # (T=4, B) layout
    lens = mx.np.array([1.0] * (LARGE_X // 10))
    m = mx.npx.sequence_mask(x, lens, use_sequence_length=True)
    assert float(m[0, 0].asnumpy()) == 1.0
    assert float(m[3, 0].asnumpy()) == 0.0


def test_grad_through_large_rows():
    """Backward over a LARGE_X-row tensor: cotangent shape/dtype clean
    (reference check_* backward halves)."""
    x = mx.np.ones((LARGE_X, 4))
    x.attach_grad()
    with autograd.record():
        y = (x * 3.0 + 1.0).sum()
    y.backward()
    g = x.grad
    assert g.shape == (LARGE_X, 4)
    assert float(g[LARGE_X - 1, 3].asnumpy()) == 3.0


def test_load_save_roundtrip(tmp_path):
    a = mx.np.full((LARGE_X, 2), 1.5)
    path = str(tmp_path / 'big.params')
    mx.nd.save(path, {'a': a})
    back = mx.nd.load(path)['a']
    assert back.shape == (LARGE_X, 2)
    assert float(back[LARGE_X - 1, 1].asnumpy()) == 1.5


def test_random_shapes():
    u = mx.np.random.uniform(size=(LARGE_X, 2))
    assert u.shape == (LARGE_X, 2)
    n = mx.np.random.normal(size=(LARGE_X,))
    assert n.shape == (LARGE_X,)
    # far-end values are populated, not zero-padding
    tail = u[LARGE_X - 3:].asnumpy()
    assert onp.isfinite(tail).all()
