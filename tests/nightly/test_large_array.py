"""Large (INT64-indexed) tensor support.

Reference: tests/nightly/test_large_array.py / test_large_vector.py —
tensors beyond 2**32 elements, gated out of CI by runtime cost (the
reference runs them nightly; CMake flag USE_INT64_TENSOR_SIZE). Here the
>4-billion-element cases are gated behind MXNET_TEST_LARGE_TENSOR=1
(needs ~18 GB host RAM); a scaled-down shape-arithmetic check always
runs so the int64 size/indexing path stays covered in CI.
"""

import os

import numpy as onp
import pytest

import mxnet_tpu as mx

LARGE = os.environ.get('MXNET_TEST_LARGE_TENSOR', '') == '1'
# reference LARGE_X = 100_000_000 rows x SMALL_Y = 50 cols
LARGE_X = 100_000_000 if LARGE else 100_000
SMALL_Y = 50


def test_int64_size_arithmetic():
    """Sizes/strides must be int64-clean even when the array itself is
    modest — the reference guards this with USE_INT64_TENSOR_SIZE."""
    a = mx.np.zeros((LARGE_X, SMALL_Y))
    assert a.size == LARGE_X * SMALL_Y
    assert a.shape == (LARGE_X, SMALL_Y)
    # indexing near the end of the flattened range
    a[LARGE_X - 1, SMALL_Y - 1] = 3.0
    assert float(a[LARGE_X - 1, SMALL_Y - 1].asnumpy()) == 3.0


@pytest.mark.skipif(not LARGE, reason='set MXNET_TEST_LARGE_TENSOR=1 '
                    '(needs ~18 GB RAM, nightly-scale)')
def test_beyond_int32_elements():
    """> 2**32 elements end to end (reference test_large_vector.py)."""
    n = 2 ** 32 + 2
    a = mx.np.ones((n,), dtype='int8')
    assert a.size == n
    s = a[n - 2:].asnumpy()
    assert s.shape == (2,)


def test_argmax_large_axis():
    x = onp.zeros((LARGE_X // 100, SMALL_Y), 'f')
    x[-1, 7] = 5.0
    a = mx.np.array(x)
    assert int(a.argmax()) == (LARGE_X // 100 - 1) * SMALL_Y + 7
