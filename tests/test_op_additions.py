"""Tests for the op-gap batch: fused RNN, im2col/col2im, space/depth ops,
numpy misc (cov/corrcoef/convolve/...), contrib matching/embedding, and the
optimizer update kernels (reference src/operator/optimizer_op.cc surface)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal

npx = mx.npx


# ------------------------------------------------------------------ fused rnn

def _pack_rnn_params(wi_list, wh_list, bi_list, bh_list):
    parts = []
    for wi, wh in zip(wi_list, wh_list):
        parts.extend([wi.ravel(), wh.ravel()])
    for bi, bh in zip(bi_list, bh_list):
        parts.extend([bi, bh])
    return np.concatenate(parts).astype('float32')


def test_rnn_lstm_matches_manual():
    T, B, I, H = 5, 3, 4, 6
    rng = np.random.default_rng(0)
    wi = rng.standard_normal((4 * H, I), dtype='f') * 0.3
    wh = rng.standard_normal((4 * H, H), dtype='f') * 0.3
    bi = rng.standard_normal(4 * H).astype('f') * 0.1
    bh = rng.standard_normal(4 * H).astype('f') * 0.1
    x = rng.standard_normal((T, B, I), dtype='f')
    h0 = np.zeros((1, B, H), 'f')
    c0 = np.zeros((1, B, H), 'f')
    params = _pack_rnn_params([wi], [wh], [bi], [bh])

    out, hy, cy = npx.rnn(mx.np.array(x), mx.np.array(params),
                          mx.np.array(h0), mx.np.array(c0), mode='lstm',
                          state_size=H, num_layers=1, state_outputs=True)

    def sig(v):
        return 1 / (1 + np.exp(-v))

    h, c = h0[0], c0[0]
    outs = []
    for t in range(T):
        g = x[t] @ wi.T + bi + h @ wh.T + bh
        i, f, gg, o = np.split(g, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(gg)
        h = sig(o) * np.tanh(c)
        outs.append(h)
    want = np.stack(outs)
    assert_almost_equal(out, want, rtol=1e-4, atol=1e-5)
    assert_almost_equal(hy, h[None], rtol=1e-4, atol=1e-5)
    assert_almost_equal(cy, c[None], rtol=1e-4, atol=1e-5)


def test_rnn_gru_matches_manual():
    T, B, I, H = 4, 2, 3, 5
    rng = np.random.default_rng(1)
    wi = rng.standard_normal((3 * H, I), dtype='f') * 0.3
    wh = rng.standard_normal((3 * H, H), dtype='f') * 0.3
    bi = rng.standard_normal(3 * H).astype('f') * 0.1
    bh = rng.standard_normal(3 * H).astype('f') * 0.1
    x = rng.standard_normal((T, B, I), dtype='f')
    h0 = np.zeros((1, B, H), 'f')
    params = _pack_rnn_params([wi], [wh], [bi], [bh])

    out, hy = npx.rnn(mx.np.array(x), mx.np.array(params), mx.np.array(h0),
                      mode='gru', state_size=H, num_layers=1,
                      state_outputs=True)

    def sig(v):
        return 1 / (1 + np.exp(-v))

    wir, wiz, win = np.split(wi, 3, 0)
    whr, whz, whn = np.split(wh, 3, 0)
    bir, biz, bin_ = np.split(bi, 3)
    bhr, bhz, bhn = np.split(bh, 3)
    h = h0[0]
    outs = []
    for t in range(T):
        r = sig(x[t] @ wir.T + bir + h @ whr.T + bhr)
        z = sig(x[t] @ wiz.T + biz + h @ whz.T + bhz)
        n = np.tanh(x[t] @ win.T + bin_ + r * (h @ whn.T + bhn))
        h = (1 - z) * n + z * h
        outs.append(h)
    assert_almost_equal(out, np.stack(outs), rtol=1e-4, atol=1e-5)


def test_rnn_bidirectional_multilayer_shapes():
    T, B, I, H, L = 6, 2, 4, 3, 2
    rng = np.random.default_rng(2)
    dirs = 2
    wi_list, wh_list, bi_list, bh_list = [], [], [], []
    for layer in range(L):
        il = I if layer == 0 else H * dirs
        for _ in range(dirs):
            wi_list.append(rng.standard_normal((4 * H, il), dtype='f') * .2)
            wh_list.append(rng.standard_normal((4 * H, H), dtype='f') * .2)
            bi_list.append(np.zeros(4 * H, 'f'))
            bh_list.append(np.zeros(4 * H, 'f'))
    params = _pack_rnn_params(wi_list, wh_list, bi_list, bh_list)
    x = rng.standard_normal((T, B, I), dtype='f')
    h0 = np.zeros((L * dirs, B, H), 'f')
    c0 = np.zeros((L * dirs, B, H), 'f')
    out, hy, cy = npx.rnn(mx.np.array(x), mx.np.array(params),
                          mx.np.array(h0), mx.np.array(c0), mode='lstm',
                          state_size=H, num_layers=L, bidirectional=True,
                          state_outputs=True)
    assert out.shape == (T, B, H * dirs)
    assert hy.shape == (L * dirs, B, H)
    assert cy.shape == (L * dirs, B, H)


def test_rnn_grad_flows():
    T, B, I, H = 3, 2, 3, 4
    rng = np.random.default_rng(3)
    nparams = 4 * H * I + 4 * H * H + 2 * 4 * H
    params = mx.np.array(rng.standard_normal(nparams, dtype='f') * 0.1)
    x = mx.np.array(rng.standard_normal((T, B, I), dtype='f'))
    h0 = mx.np.zeros((1, B, H))
    c0 = mx.np.zeros((1, B, H))
    params.attach_grad()
    with mx.autograd.record():
        out = npx.rnn(x, params, h0, c0, mode='lstm', state_size=H,
                      num_layers=1)
        loss = (out * out).sum()
    loss.backward()
    g = params.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


# ------------------------------------------------------------- im2col/col2im

def test_im2col_matches_naive():
    N, C, Hh, W = 2, 3, 5, 5
    k, s, p = (3, 3), (1, 1), (1, 1)
    x = np.random.uniform(-1, 1, (N, C, Hh, W)).astype('f')
    got = npx.im2col(mx.np.array(x), kernel=k, stride=s, pad=p).asnumpy()
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    oh = ow = 5
    want = np.zeros((N, C * 9, oh * ow), 'f')
    for c in range(C):
        for ki in range(3):
            for kj in range(3):
                row = c * 9 + ki * 3 + kj
                patch = xp[:, c, ki:ki + oh, kj:kj + ow]
                want[:, row, :] = patch.reshape(N, -1)
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-6)


def test_col2im_is_adjoint_of_im2col():
    N, C, Hh, W = 1, 2, 4, 4
    k, s = (2, 2), (2, 2)
    x = np.random.uniform(-1, 1, (N, C, Hh, W)).astype('f')
    cols = npx.im2col(mx.np.array(x), kernel=k, stride=s)
    y = np.random.uniform(-1, 1, cols.shape).astype('f')
    back = npx.col2im(mx.np.array(y), output_size=(Hh, W), kernel=k,
                      stride=s)
    # <im2col(x), y> == <x, col2im(y)> (adjoint identity)
    lhs = float((cols.asnumpy() * y).sum())
    rhs = float((x * back.asnumpy()).sum())
    assert abs(lhs - rhs) < 1e-3


# --------------------------------------------------------- depth/space, misc

def test_depth_space_roundtrip():
    x = np.arange(2 * 8 * 3 * 3, dtype='f').reshape(2, 8, 3, 3)
    d = mx.np.array(x)
    up = npx.depth_to_space(d, 2)
    assert up.shape == (2, 2, 6, 6)
    back = npx.space_to_depth(up, 2)
    assert_almost_equal(back, x)


def test_arange_like():
    x = mx.np.zeros((2, 3))
    out = npx.arange_like(x, start=1.0, step=0.5)
    assert out.shape == (2, 3)
    assert_almost_equal(out, 1.0 + 0.5 * np.arange(6).reshape(2, 3))
    row = npx.arange_like(x, axis=1)
    assert_almost_equal(row, np.arange(3, dtype='f'))
    rep = npx.arange_like(x, repeat=2)
    assert rep.shape == (2, 3)
    assert_almost_equal(rep, np.array([[0, 0, 1], [1, 2, 2]], 'f'))
    rep_ax = npx.arange_like(x, axis=1, repeat=3)
    assert_almost_equal(rep_ax, np.zeros(3, 'f'))


@pytest.mark.parametrize('name,args', [
    ('vander', (np.array([1., 2., 3.]),)),
    ('unwrap', (np.array([0., 0.5, 6.5, 7.0]),)),
    ('convolve', (np.array([1., 2., 3.]), np.array([0., 1., 0.5]))),
    ('correlate', (np.array([1., 2., 3.]), np.array([0., 1., 0.5]))),
    ('cov', (np.random.uniform(size=(3, 8)).astype('f'),)),
    ('corrcoef', (np.random.uniform(size=(3, 8)).astype('f'),)),
])
def test_numpy_misc_parity(name, args):
    got = getattr(mx.np, name)(*[mx.np.array(a) for a in args])
    want = getattr(np, name)(*args)
    assert_almost_equal(got, want, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------------- contrib

def test_bipartite_matching():
    score = np.array([[[0.9, 0.1], [0.8, 0.7]]], 'f')
    row, col = npx.bipartite_matching(mx.np.array(score), threshold=0.5)
    # greedy: (0,0)=0.9 first, then (1,1)=0.7
    assert row.asnumpy().tolist() == [[0.0, 1.0]]
    assert col.asnumpy().tolist() == [[0.0, 1.0]]


def test_sparse_embedding():
    W = np.random.uniform(size=(10, 4)).astype('f')
    idx = np.array([[1, 3], [5, 0]], 'f')
    out = npx.sparse_embedding(mx.np.array(idx), mx.np.array(W))
    assert_almost_equal(out, W[idx.astype(int)])


# ---------------------------------------------------------- optimizer kernels

def test_sgd_and_momentum_update():
    w = np.array([1.0, 2.0], 'f')
    g = np.array([0.5, -0.5], 'f')
    out = npx.sgd_update(mx.np.array(w), mx.np.array(g), lr=0.1, wd=0.0)
    assert_almost_equal(out, w - 0.1 * g)
    m = np.zeros(2, 'f')
    w2, m2 = npx.sgd_mom_update(mx.np.array(w), mx.np.array(g),
                                mx.np.array(m), lr=0.1, momentum=0.9)
    assert_almost_equal(m2, -0.1 * g)
    assert_almost_equal(w2, w - 0.1 * g)


def test_adam_update_matches_reference_formula():
    rng = np.random.default_rng(0)
    w = rng.standard_normal(5).astype('f')
    g = rng.standard_normal(5).astype('f')
    mean = np.zeros(5, 'f')
    var = np.zeros(5, 'f')
    w2, m2, v2 = npx.adam_update(mx.np.array(w), mx.np.array(g),
                                 mx.np.array(mean), mx.np.array(var),
                                 lr=0.01)
    em = 0.1 * g
    ev = 0.001 * g * g
    assert_almost_equal(m2, em, rtol=1e-5, atol=1e-6)
    assert_almost_equal(v2, ev, rtol=1e-5, atol=1e-6)
    assert_almost_equal(w2, w - 0.01 * em / (np.sqrt(ev) + 1e-8),
                        rtol=1e-5, atol=1e-6)


def test_adamw_decoupled_decay():
    w = np.ones(3, 'f')
    g = np.zeros(3, 'f')
    w2, _, _ = npx.adamw_update(mx.np.array(w), mx.np.array(g),
                                mx.np.zeros(3), mx.np.zeros(3),
                                lr=0.1, wd=0.01, eta=1.0)
    assert_almost_equal(w2, w - 0.01 * w, rtol=1e-5, atol=1e-7)


def test_multi_sgd_and_sum_sq():
    ws = [np.array([1.0], 'f'), np.array([2.0, 3.0], 'f')]
    gs = [np.array([0.1], 'f'), np.array([0.2, 0.3], 'f')]
    arrays = [mx.np.array(a) for pair in zip(ws, gs) for a in pair]
    o1, o2 = npx.multi_sgd_update(*arrays, lrs=(0.1, 0.2), wds=(0.0, 0.0),
                                  num_weights=2)
    assert_almost_equal(o1, ws[0] - 0.1 * gs[0])
    assert_almost_equal(o2, ws[1] - 0.2 * gs[1])
    ss = npx.multi_sum_sq(*[mx.np.array(w) for w in ws])
    assert_almost_equal(ss, np.array([1.0, 13.0], 'f'))


def test_group_adagrad_update():
    w = np.ones((2, 3), 'f')
    g = np.full((2, 3), 0.5, 'f')
    h = np.zeros((2, 1), 'f')
    w2, h2 = npx.group_adagrad_update(mx.np.array(w), mx.np.array(g),
                                      mx.np.array(h), lr=0.1)
    assert_almost_equal(h2, np.full((2, 1), 0.25, 'f'))
    assert_almost_equal(w2, w - 0.1 * 0.5 / (0.5 + 1e-5), rtol=1e-4,
                        atol=1e-5)


def test_lamb_phases():
    w = np.ones(4, 'f') * 2
    g = np.ones(4, 'f') * 0.1
    gdir, mean, var = npx.lamb_update_phase1(
        mx.np.array(w), mx.np.array(g), mx.np.zeros(4), mx.np.zeros(4), t=1)
    assert_almost_equal(mean, 0.1 * g, rtol=1e-5, atol=1e-7)
    assert_almost_equal(var, 0.001 * g * g, rtol=1e-5, atol=1e-9)
    r1 = mx.np.array(np.array(np.linalg.norm(w), 'f'))
    r2 = mx.np.array(np.array(np.linalg.norm(gdir.asnumpy()), 'f'))
    w2 = npx.lamb_update_phase2(mx.np.array(w), gdir, r1, r2, lr=0.01)
    assert np.isfinite(w2.asnumpy()).all()
    assert (w2.asnumpy() < w).all()
