"""Test configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY §4 pattern: single-host
multi-process + mocked mesh for CI, real pod for nightly).

The environment registers the axon (TPU tunnel) PJRT plugin into every
interpreter via sitecustomize; initializing it from a second process can
block on the single TPU grant. CPU tests must never touch it, so the axon
factory is removed from jax's backend registry before any backend
initializes. This must run before any test imports mxnet_tpu/jax ops.
"""

import os

flags = os.environ.get('XLA_FLAGS', '')
if 'host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()

if os.environ.get('MXNET_TEST_DEVICE', 'cpu') == 'cpu':
    import jax
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop('axon', None)
    _xb._backend_factories.pop('tpu', None)
    os.environ['JAX_PLATFORMS'] = ''
    jax.config.update('jax_platforms', 'cpu')

import numpy as _np
import pytest


@pytest.fixture(autouse=True)
def _seed_rngs():
    """Reproducible RNG per test (reference tests common.py:164 with_seed)."""
    import mxnet_tpu as mx
    seed = int(os.environ.get('MXNET_TEST_SEED', '42'))
    _np.random.seed(seed)
    mx.random.seed(seed)
    yield
