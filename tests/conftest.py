"""Test configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY §4 pattern: single-host
multi-process + mocked mesh for CI, real pod for nightly).

The environment registers the axon (TPU tunnel) PJRT plugin into every
interpreter via sitecustomize; initializing it from a second process can
block on the single TPU grant. CPU tests must never touch it, so the axon
factory is removed from jax's backend registry before any backend
initializes. This must run before any test imports mxnet_tpu/jax ops.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get('MXNET_TEST_DEVICE', 'cpu') == 'cpu':
    import _cpu_guard
    _cpu_guard.force_cpu(8)

import numpy as _np
import pytest


@pytest.fixture(autouse=True)
def _seed_rngs():
    """Reproducible RNG per test (reference tests common.py:164 with_seed)."""
    import mxnet_tpu as mx
    seed = int(os.environ.get('MXNET_TEST_SEED', '42'))
    _np.random.seed(seed)
    mx.random.seed(seed)
    yield
