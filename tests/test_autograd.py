"""Autograd (reference tests/python/unittest/test_autograd.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_basic_backward():
    x = mx.np.array([1., 2., 3.])
    x.attach_grad()
    with autograd.record():
        y = (x * x * 2).sum()
    y.backward()
    assert_almost_equal(x.grad, 4 * np.array([1., 2., 3.]))


def test_chain_rule():
    x = mx.np.array([0.5, -0.5])
    x.attach_grad()
    with autograd.record():
        y = mx.np.exp(mx.np.sin(x)).sum()
    y.backward()
    want = np.exp(np.sin([0.5, -0.5])) * np.cos([0.5, -0.5])
    assert_almost_equal(x.grad, want, rtol=1e-5)


def test_out_grad():
    x = mx.np.array([1., 2.])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(mx.np.array([10., 100.]))
    assert_almost_equal(x.grad, [30., 300.])


def test_grad_req_add():
    x = mx.np.array([1., 1.])
    x.attach_grad(grad_req='add')
    for _ in range(3):
        with autograd.record():
            y = (x * 2).sum()
        y.backward()
    assert_almost_equal(x.grad, [6., 6.])


def test_multiple_variables():
    a = mx.np.array([2.])
    b = mx.np.array([3.])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        y = a * b + a
    y.backward()
    assert_almost_equal(a.grad, [4.])   # b + 1
    assert_almost_equal(b.grad, [2.])   # a


def test_grad_function():
    x = mx.np.array([1., 2., 3.])
    x.attach_grad()
    with autograd.record():
        y = (x ** 3).sum()
    g = autograd.grad(y, x)
    assert_almost_equal(g, 3 * np.array([1., 4., 9.]))
    # .grad buffer untouched by autograd.grad
    assert_almost_equal(x.grad, np.zeros(3))


def test_detach_and_stop_gradient():
    x = mx.np.array([2.])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, [4.])  # only d(y_const*x)/dx = y = 4
    x2 = mx.np.array([2.])
    x2.attach_grad()
    with autograd.record():
        w = mx.nd.stop_gradient(x2 * x2) * x2
    w.backward()
    assert_almost_equal(x2.grad, [4.])


def test_pause_and_modes():
    x = mx.np.array([1.])
    x.attach_grad()
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
            y_nograd = x * 5
        y = x * 2
    assert y_nograd._ag is None
    y.backward()
    assert_almost_equal(x.grad, [2.])
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
    with autograd.predict_mode():
        assert not autograd.is_training()


def test_retain_graph():
    x = mx.np.array([3.])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    assert_almost_equal(x.grad, [6.])
    y.backward()
    assert_almost_equal(x.grad, [6.])  # write req overwrites


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = mx.np.array(1.0 / (1.0 + np.exp(-x.asnumpy())))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = mx.np.array([0.0, 1.0, -1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-np.array([0.0, 1.0, -1.0])))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-4)


def test_numeric_gradient():
    check_numeric_gradient(lambda x: (x * x + 3 * x).sum(),
                           [np.random.randn(2, 3).astype('float32')])


def test_grad_through_matmul():
    a = np.random.randn(3, 4).astype('float32')
    w = mx.np.array(np.random.randn(4, 2).astype('float32'))
    w.attach_grad()
    with autograd.record():
        out = (mx.np.dot(mx.np.array(a), w)).sum()
    out.backward()
    assert_almost_equal(w.grad, a.sum(0)[:, None].repeat(2, 1), rtol=1e-4)


def test_mark_variables_api():
    x = mx.np.array([1.])
    g = mx.np.zeros((1,))
    autograd.mark_variables(x, g)
    with autograd.record():
        y = x * 7
    y.backward()
    assert_almost_equal(x.grad, [7.])
