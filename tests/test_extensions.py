"""Custom ops / mx.library / mx.rtc tests (reference coverage:
test_operator.py Custom-op tests, rtc tests in tests/python/gpu/)."""

import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


@mx.operator.register('sigmoid_custom')
class SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return SigmoidOp()


class SigmoidOp(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0]
        y = 1.0 / (1.0 + mx.np.exp(-x))
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1.0 - y))


def test_custom_op_forward():
    x = mx.np.array([0.0, 1.0, -1.0])
    y = mx.nd.Custom(x, op_type='sigmoid_custom')
    onp.testing.assert_allclose(
        y.asnumpy(), 1 / (1 + onp.exp(-x.asnumpy())), rtol=1e-6)


def test_custom_op_backward():
    x = mx.np.array([0.5, -0.5])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type='sigmoid_custom')
        loss = y.sum()
    loss.backward()
    s = 1 / (1 + onp.exp(-x.asnumpy()))
    onp.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_library_load_python_extension(tmp_path):
    ext = tmp_path / 'myext.py'
    ext.write_text(
        'from mxnet_tpu.ops.registry import register\n'
        'import jax.numpy as jnp\n'
        "@register('myext_triple')\n"
        'def myext_triple(x):\n'
        '    return 3 * x\n')
    mx.library.load(str(ext))
    from mxnet_tpu.ops.registry import get_op, invoke
    out = invoke(get_op('myext_triple'), (mx.np.array([1.0, 2.0]),), {})
    onp.testing.assert_allclose(out.asnumpy(), [3, 6])


def test_rtc_pallas_module():
    src = '''
def double_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0
'''
    mod = mx.rtc.PallasModule(src)
    kern = mod.get_kernel('double_kernel')
    x = mx.np.array(onp.arange(8.0, dtype='float32').reshape(8, 1))
    (out,) = [kern.launch([x], out_shapes=(8, 1))]
    onp.testing.assert_allclose(out.asnumpy(), x.asnumpy() * 2)


def test_rtc_unknown_kernel():
    mod = mx.rtc.PallasModule('def k(a_ref, o_ref):\n    o_ref[...] = a_ref[...]\n')
    with pytest.raises(KeyError):
        mod.get_kernel('nope')


def test_custom_op_runs_on_worker_async():
    """Reference custom-inl.h:52: the user forward runs on a dedicated
    worker; custom() returns immediately with pending outputs and
    results materialize at the sync point."""
    import threading
    import time

    started = threading.Event()
    release = threading.Event()

    @mx.operator.register('slow_scale')
    class SlowScaleProp(mx.operator.CustomOpProp):
        def list_arguments(self):
            return ['data']

        def list_outputs(self):
            return ['out']

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            outer_started, outer_release = started, release

            class SlowScale(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    outer_started.set()
                    assert outer_release.wait(timeout=30)
                    self.assign(out_data[0], req[0], in_data[0] * 3.0)

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0] * 3.0)
            return SlowScale()

    x = mx.np.array([1.0, 2.0])
    t0 = time.perf_counter()
    y = mx.nd.Custom(x, op_type='slow_scale')
    issued = time.perf_counter() - t0
    # the call returned while the user forward is still blocked
    assert started.wait(timeout=10)
    assert issued < 5.0
    assert y.shape == (2,)                  # shape known pre-sync
    release.set()
    onp.testing.assert_allclose(y.asnumpy(), [3.0, 6.0])


def test_custom_op_exception_routed_to_sync_point():
    """User-code exceptions surface when the result is awaited, not at
    dispatch (threaded_engine.h:365 exception-at-sync-point)."""
    @mx.operator.register('boom_op')
    class BoomProp(mx.operator.CustomOpProp):
        def list_arguments(self):
            return ['data']

        def list_outputs(self):
            return ['out']

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            class Boom(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    raise ValueError('user forward exploded')
            return Boom()

    y = mx.nd.Custom(mx.np.ones((2,)), op_type='boom_op')  # no raise here
    with pytest.raises(RuntimeError, match='boom_op'):
        y.asnumpy()


def test_custom_op_fifo_chaining():
    """Chained custom ops: the second consumes the first's PENDING
    output. Dispatch must not block (the pending input is snapshotted
    by LazyRef and resolved on the worker, where FIFO order guarantees
    the earlier op's value is already set)."""
    import time
    @mx.operator.register('plus_one')
    class PlusOneProp(mx.operator.CustomOpProp):
        def list_arguments(self):
            return ['data']

        def list_outputs(self):
            return ['out']

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            class PlusOne(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    time.sleep(0.1)
                    self.assign(out_data[0], req[0], in_data[0] + 1.0)

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0])
            return PlusOne()

    x = mx.np.zeros((3,))
    y = x
    t0 = time.perf_counter()
    for _ in range(5):
        y = mx.nd.Custom(y, op_type='plus_one')
    issued = time.perf_counter() - t0
    # 5 chained dispatches of a 0.1s op: dispatching must not serialize
    # on the worker (a blocking snapshot would take >= 0.4s here)
    assert issued < 0.3, f'chained dispatch blocked: {issued:.2f}s'
    onp.testing.assert_allclose(y.asnumpy(), [5.0, 5.0, 5.0])
