"""Custom ops / mx.library / mx.rtc tests (reference coverage:
test_operator.py Custom-op tests, rtc tests in tests/python/gpu/)."""

import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


@mx.operator.register('sigmoid_custom')
class SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return SigmoidOp()


class SigmoidOp(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0]
        y = 1.0 / (1.0 + mx.np.exp(-x))
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1.0 - y))


def test_custom_op_forward():
    x = mx.np.array([0.0, 1.0, -1.0])
    y = mx.nd.Custom(x, op_type='sigmoid_custom')
    onp.testing.assert_allclose(
        y.asnumpy(), 1 / (1 + onp.exp(-x.asnumpy())), rtol=1e-6)


def test_custom_op_backward():
    x = mx.np.array([0.5, -0.5])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type='sigmoid_custom')
        loss = y.sum()
    loss.backward()
    s = 1 / (1 + onp.exp(-x.asnumpy()))
    onp.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_library_load_python_extension(tmp_path):
    ext = tmp_path / 'myext.py'
    ext.write_text(
        'from mxnet_tpu.ops.registry import register\n'
        'import jax.numpy as jnp\n'
        "@register('myext_triple')\n"
        'def myext_triple(x):\n'
        '    return 3 * x\n')
    mx.library.load(str(ext))
    from mxnet_tpu.ops.registry import get_op, invoke
    out = invoke(get_op('myext_triple'), (mx.np.array([1.0, 2.0]),), {})
    onp.testing.assert_allclose(out.asnumpy(), [3, 6])


def test_rtc_pallas_module():
    src = '''
def double_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0
'''
    mod = mx.rtc.PallasModule(src)
    kern = mod.get_kernel('double_kernel')
    x = mx.np.array(onp.arange(8.0, dtype='float32').reshape(8, 1))
    (out,) = [kern.launch([x], out_shapes=(8, 1))]
    onp.testing.assert_allclose(out.asnumpy(), x.asnumpy() * 2)


def test_rtc_unknown_kernel():
    mod = mx.rtc.PallasModule('def k(a_ref, o_ref):\n    o_ref[...] = a_ref[...]\n')
    with pytest.raises(KeyError):
        mod.get_kernel('nope')
