"""Gluon-surface pipeline parallelism (VERDICT r4 weak #3 / next #5):
a real Gluon net trains through PipelineTrainer on the CPU mesh, with
1F1B gradients matching the eager autograd reference."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, parallel
from mxnet_tpu.gluon import nn

D, MB, NMICRO = 8, 2, 4


def _stage(seed):
    mx.random.seed(seed)
    s = nn.Dense(D, activation='tanh', in_units=D)
    s.initialize()
    s(mx.np.zeros((MB, D)))
    return s


def _data():
    rng = np.random.default_rng(0)
    xs = mx.np.array(rng.standard_normal((NMICRO, MB, D)).astype('f'))
    ys = mx.np.array(rng.standard_normal((NMICRO, MB, D)).astype('f'))
    return xs, ys


def _eager_grads(stages, xs, ys):
    """Reference: sum of per-microbatch squared errors through the
    stages, eager autograd."""
    with autograd.record():
        total = None
        for i in range(NMICRO):
            h = xs[i]
            for st in stages:
                h = st(h)
            e = ((h - ys[i]) ** 2).sum()
            total = e if total is None else total + e
    total.backward()
    grads = {}
    for s, st in enumerate(stages):
        for name, p in st.collect_params().items():
            grads[(s, name)] = p.grad().asnumpy().copy()
    return float(total.asnumpy()), grads


def test_pipeline_trainer_1f1b_matches_eager_and_updates():
    mesh = parallel.make_mesh(pp=2)
    stages = [_stage(1), _stage(2)]
    xs, ys = _data()
    want_loss, want_grads = _eager_grads(stages, xs, ys)
    w0 = {(s, n): p.data().asnumpy().copy()
          for s, st in enumerate(stages)
          for n, p in st.collect_params().items()}

    lr, bs = 0.1, NMICRO * MB
    trainer = parallel.PipelineTrainer(
        stages, mesh, example=mx.np.zeros((MB, D)),
        optimizer='sgd', optimizer_params={'learning_rate': lr})
    loss = trainer.step(xs, ys)
    assert loss == pytest.approx(want_loss, rel=1e-4)
    for s, st in enumerate(stages):
        for n, p in st.collect_params().items():
            # grads written into the Parameter buffers match eager
            np.testing.assert_allclose(p.grad().asnumpy(),
                                       want_grads[(s, n)],
                                       rtol=1e-4, atol=1e-5)
            # and SGD applied them: w1 = w0 - lr * g / batch_size
            np.testing.assert_allclose(
                p.data().asnumpy(),
                w0[(s, n)] - lr * want_grads[(s, n)] / bs,
                rtol=1e-4, atol=1e-5)


def test_pipeline_trainer_loss_decreases():
    mesh = parallel.make_mesh(pp=2)
    stages = [_stage(3), _stage(4)]
    xs, ys = _data()
    trainer = parallel.PipelineTrainer(
        stages, mesh, example=mx.np.zeros((MB, D)),
        optimizer='sgd', optimizer_params={'learning_rate': 0.5})
    losses = [trainer.step(xs, ys) for _ in range(6)]
    assert losses[-1] < losses[0] * 0.8, losses


def test_pipeline_trainer_gpipe_matches_1f1b():
    """Both schedules are the same math on the same workload — updated
    parameters must agree."""
    mesh = parallel.make_mesh(pp=2)
    xs, ys = _data()
    updated = {}
    for sched in ('1f1b', 'gpipe'):
        stages = [_stage(5), _stage(6)]     # same seeds -> same init
        tr = parallel.PipelineTrainer(
            stages, mesh, example=mx.np.zeros((MB, D)),
            optimizer='sgd', optimizer_params={'learning_rate': 0.2},
            schedule=sched)
        tr.step(xs, ys)
        updated[sched] = {(s, n): p.data().asnumpy().copy()
                          for s, st in enumerate(stages)
                          for n, p in st.collect_params().items()}
    for k in updated['1f1b']:
        np.testing.assert_allclose(updated['1f1b'][k],
                                   updated['gpipe'][k],
                                   rtol=1e-4, atol=1e-5)


def test_split_sequential_and_forward():
    mesh = parallel.make_mesh(pp=2)
    mx.random.seed(7)
    net = nn.HybridSequential()
    for _ in range(4):
        net.add(nn.Dense(D, activation='tanh', in_units=D))
    net.initialize()
    net(mx.np.zeros((MB, D)))
    stages = parallel.split_sequential(net, 2)
    assert len(stages) == 2
    xs, _ = _data()
    tr = parallel.PipelineTrainer(
        stages, mesh, example=mx.np.zeros((MB, D)))
    out = tr.forward(xs)
    # pipelined forward == the plain sequential net on every microbatch
    for i in range(NMICRO):
        with autograd.predict_mode():
            want = net(xs[i]).asnumpy()
        np.testing.assert_allclose(np.asarray(out.asnumpy())[i], want,
                                   rtol=1e-5, atol=1e-6)


def test_pipeline_trainer_rejects_batchnorm_stage():
    mesh = parallel.make_mesh(pp=2)
    sbn = nn.HybridSequential()
    sbn.add(nn.Dense(D, in_units=D), nn.BatchNorm())
    sbn.initialize()
    sbn(mx.np.zeros((MB, D)))
    with pytest.raises(ValueError, match='aux state'):
        parallel.PipelineTrainer(
            [sbn, sbn], mesh, example=mx.np.zeros((MB, D)))
