"""Sharded checkpoint/resume over the virtual 8-device CPU mesh.

Reference gap this covers (SURVEY §5): MXNet checkpoints are rank-0 whole
files; the TPU build checkpoints sharded parameters collectively."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.test_utils import assert_almost_equal


def _mesh():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ('dp', 'tp'))


def test_save_restore_roundtrip_sharded(tmp_path):
    mesh = _mesh()
    rng = np.random.default_rng(0)
    tree = {
        'w1': jax.device_put(
            jnp.asarray(rng.standard_normal((8, 16), dtype=np.float32)),
            NamedSharding(mesh, P(None, 'tp'))),
        'b1': jax.device_put(
            jnp.asarray(rng.standard_normal(16, dtype=np.float32)),
            NamedSharding(mesh, P())),
    }
    path = str(tmp_path / 'ckpt')
    parallel.save_sharded(path, tree)

    restored = parallel.restore_sharded(path, template=tree)
    for k in tree:
        assert_almost_equal(np.asarray(restored[k]), np.asarray(tree[k]))
        assert restored[k].sharding == tree[k].sharding


def test_restore_with_new_sharding(tmp_path):
    mesh = _mesh()
    w = jax.device_put(jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
                       NamedSharding(mesh, P('dp', None)))
    path = str(tmp_path / 'ckpt2')
    parallel.save_sharded(path, {'w': w})

    # restore re-sharded over tp instead of dp
    tmpl = {'w': jax.ShapeDtypeStruct(
        (8, 4), jnp.float32, sharding=NamedSharding(mesh, P(None, 'tp')))}
    restored = parallel.restore_sharded(path, template=tmpl)
    assert restored['w'].sharding.spec == P(None, 'tp')
    assert_almost_equal(np.asarray(restored['w']), np.asarray(w))


def test_restore_to_host_numpy(tmp_path):
    tree = {'a': jnp.ones((3, 3)), 'nested': {'b': jnp.zeros(4)}}
    path = str(tmp_path / 'ckpt3')
    parallel.save_sharded(path, tree)
    out = parallel.restore_sharded(path)
    assert_almost_equal(np.asarray(out['a']), np.ones((3, 3)))
    assert_almost_equal(np.asarray(out['nested']['b']), np.zeros(4))


def test_checkpoint_manager_rotation(tmp_path):
    mgr = parallel.SharedCheckpointManager(str(tmp_path / 'mgr'),
                                           max_to_keep=2)
    try:
        for step in range(4):
            mgr.save(step, {'w': jnp.full((2,), float(step))})
        steps = mgr.all_steps()
        assert mgr.latest_step() == 3
        assert len(steps) <= 2 and 3 in steps
        out = mgr.restore()
        assert_almost_equal(np.asarray(out['w']), np.full((2,), 3.0))
    finally:
        mgr.close()


def test_block_params_sharded_roundtrip(tmp_path):
    from mxnet_tpu.parallel.checkpoint import (save_params_sharded,
                                               load_params_sharded)
    net = mx.gluon.nn.Dense(8, in_units=4)
    net.initialize()
    before = {k: v.data().asnumpy() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'blk')
    save_params_sharded(path, net)
    # perturb, then restore
    for _, p in net.collect_params().items():
        p.set_data(mx.np.zeros(p.shape))
    load_params_sharded(path, net)
    after = {k: v.data().asnumpy() for k, v in net.collect_params().items()}
    for k in before:
        assert_almost_equal(after[k], before[k])


# ------------------------------------------------- crash-atomic commit

class _Killed(BaseException):
    """Stands in for SIGKILL: aborts the save at an exact commit-protocol
    point, leaving the filesystem exactly as a real kill would."""


def _kill_at(point):
    from mxnet_tpu.parallel import checkpoint as C

    def hook(name):
        if name == point:
            raise _Killed(point)
    return C.install_crash_hook(hook)


def _saved_tree(v):
    return {'w': jnp.full((2,), float(v))}


@pytest.mark.parametrize('point', ['ckpt.staged', 'ckpt.renamed'])
def test_kill_mid_save_keeps_previous_checkpoint(tmp_path, point):
    """A kill after the staging write, or even after the atomic rename
    but before the manifest commit, must leave ``latest_step()`` on the
    previous complete checkpoint — the manifest is the only source of
    truth, and it is written last."""
    from mxnet_tpu.parallel import checkpoint as C
    d = str(tmp_path / 'crash')
    mgr = parallel.SharedCheckpointManager(d, max_to_keep=3)
    mgr.save(0, _saved_tree(0))
    prev = _kill_at(point)
    try:
        with pytest.raises(_Killed):
            mgr.save(1, _saved_tree(1))
    finally:
        C.install_crash_hook(prev)
    assert mgr.latest_step() == 0
    # the "restarted process": a fresh manager sweeps staging debris
    # and still restores the previous complete checkpoint
    mgr2 = parallel.SharedCheckpointManager(d, max_to_keep=3)
    assert mgr2.latest_step() == 0
    assert not any(n.startswith('.staging-') or n == '.MANIFEST.tmp'
                   for n in __import__('os').listdir(d))
    assert_almost_equal(np.asarray(mgr2.restore()['w']), np.zeros(2))
    # and the interrupted step can be re-saved cleanly
    mgr2.save(1, _saved_tree(1))
    assert mgr2.latest_step() == 1
    assert_almost_equal(np.asarray(mgr2.restore()['w']), np.ones(2))


def test_kill_after_manifest_commit_keeps_new_checkpoint(tmp_path):
    """Past the manifest rename the checkpoint IS committed: a kill in
    the cleanup tail (pruning old steps) must not lose it."""
    from mxnet_tpu.parallel import checkpoint as C
    d = str(tmp_path / 'crash2')
    mgr = parallel.SharedCheckpointManager(d, max_to_keep=3)
    mgr.save(0, _saved_tree(0))
    prev = _kill_at('ckpt.committed')
    try:
        with pytest.raises(_Killed):
            mgr.save(1, _saved_tree(1))
    finally:
        C.install_crash_hook(prev)
    mgr2 = parallel.SharedCheckpointManager(d, max_to_keep=3)
    assert mgr2.latest_step() == 1
    assert_almost_equal(np.asarray(mgr2.restore()['w']), np.ones(2))


def test_kill_at_every_point_never_corrupts_latest(tmp_path):
    """The acceptance sweep: kill the save at EVERY protocol point in
    turn; after each, ``latest_step()`` must be either the previous or
    the new complete checkpoint and must restore cleanly."""
    from mxnet_tpu.parallel import checkpoint as C
    d = str(tmp_path / 'sweep')
    mgr = parallel.SharedCheckpointManager(d, max_to_keep=2)
    mgr.save(0, _saved_tree(0))
    committed = 0
    for step, point in enumerate(
            ['ckpt.staged', 'ckpt.renamed', 'ckpt.committed'], start=1):
        prev = _kill_at(point)
        try:
            with pytest.raises(_Killed):
                mgr.save(step, _saved_tree(step))
        finally:
            C.install_crash_hook(prev)
        if point == 'ckpt.committed':
            committed = step
        fresh = parallel.SharedCheckpointManager(d, max_to_keep=2)
        assert fresh.latest_step() == committed
        got = np.asarray(fresh.restore()['w'])
        assert_almost_equal(got, np.full((2,), float(committed)))
        mgr = fresh


def test_kill_while_resaving_committed_step_never_tears_manifest(tmp_path):
    """Re-saving a step that is ALREADY committed (the restored step
    after a rollback) deletes the existing step directory before the
    rename. A kill in that window must not leave the manifest pointing
    at the deleted directory: the step is un-committed from the
    manifest first, so ``latest_step()`` falls back to the previous
    complete checkpoint and restores cleanly."""
    from mxnet_tpu.parallel import checkpoint as C
    d = str(tmp_path / 'resave')
    mgr = parallel.SharedCheckpointManager(d, max_to_keep=3)
    mgr.save(0, _saved_tree(0))
    mgr.save(1, _saved_tree(1))
    prev = _kill_at('ckpt.cleared')
    try:
        with pytest.raises(_Killed):
            mgr.save(1, _saved_tree(41))        # re-save committed step
    finally:
        C.install_crash_hook(prev)
    mgr2 = parallel.SharedCheckpointManager(d, max_to_keep=3)
    assert mgr2.latest_step() == 0              # never the torn step 1
    assert_almost_equal(np.asarray(mgr2.restore()['w']), np.zeros(2))
    # and the re-save goes through cleanly on retry
    mgr2.save(1, _saved_tree(41))
    assert mgr2.latest_step() == 1
    assert_almost_equal(np.asarray(mgr2.restore()['w']),
                        np.full((2,), 41.0))


def test_manifest_missing_falls_back_to_legacy_scan(tmp_path):
    """Checkpoint dirs written before the manifest protocol (no
    MANIFEST.json) are still discovered by the integer-dir scan."""
    import os as _os
    d = str(tmp_path / 'legacy')
    mgr = parallel.SharedCheckpointManager(d)
    mgr.save(3, _saved_tree(3))
    _os.remove(_os.path.join(d, 'MANIFEST.json'))
    mgr2 = parallel.SharedCheckpointManager(d)
    assert mgr2.latest_step() == 3
    assert_almost_equal(np.asarray(mgr2.restore()['w']),
                        np.full((2,), 3.0))


def test_restore_or_init(tmp_path):
    from mxnet_tpu.parallel.checkpoint import restore_or_init
    mgr = parallel.SharedCheckpointManager(str(tmp_path / 'el'),
                                           max_to_keep=2)
    try:
        state, step = restore_or_init(mgr, lambda: {'w': jnp.zeros(2)})
        assert step == -1 and float(state['w'][0]) == 0.0
        mgr.save(5, {'w': jnp.full((2,), 7.0)})
        state, step = restore_or_init(mgr, lambda: {'w': jnp.zeros(2)})
        assert step == 5
        assert_almost_equal(np.asarray(state['w']), np.full((2,), 7.0))
    finally:
        mgr.close()
