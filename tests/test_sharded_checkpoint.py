"""Sharded checkpoint/resume over the virtual 8-device CPU mesh.

Reference gap this covers (SURVEY §5): MXNet checkpoints are rank-0 whole
files; the TPU build checkpoints sharded parameters collectively."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.test_utils import assert_almost_equal


def _mesh():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ('dp', 'tp'))


def test_save_restore_roundtrip_sharded(tmp_path):
    mesh = _mesh()
    rng = np.random.default_rng(0)
    tree = {
        'w1': jax.device_put(
            jnp.asarray(rng.standard_normal((8, 16), dtype=np.float32)),
            NamedSharding(mesh, P(None, 'tp'))),
        'b1': jax.device_put(
            jnp.asarray(rng.standard_normal(16, dtype=np.float32)),
            NamedSharding(mesh, P())),
    }
    path = str(tmp_path / 'ckpt')
    parallel.save_sharded(path, tree)

    restored = parallel.restore_sharded(path, template=tree)
    for k in tree:
        assert_almost_equal(np.asarray(restored[k]), np.asarray(tree[k]))
        assert restored[k].sharding == tree[k].sharding


def test_restore_with_new_sharding(tmp_path):
    mesh = _mesh()
    w = jax.device_put(jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
                       NamedSharding(mesh, P('dp', None)))
    path = str(tmp_path / 'ckpt2')
    parallel.save_sharded(path, {'w': w})

    # restore re-sharded over tp instead of dp
    tmpl = {'w': jax.ShapeDtypeStruct(
        (8, 4), jnp.float32, sharding=NamedSharding(mesh, P(None, 'tp')))}
    restored = parallel.restore_sharded(path, template=tmpl)
    assert restored['w'].sharding.spec == P(None, 'tp')
    assert_almost_equal(np.asarray(restored['w']), np.asarray(w))


def test_restore_to_host_numpy(tmp_path):
    tree = {'a': jnp.ones((3, 3)), 'nested': {'b': jnp.zeros(4)}}
    path = str(tmp_path / 'ckpt3')
    parallel.save_sharded(path, tree)
    out = parallel.restore_sharded(path)
    assert_almost_equal(np.asarray(out['a']), np.ones((3, 3)))
    assert_almost_equal(np.asarray(out['nested']['b']), np.zeros(4))


def test_checkpoint_manager_rotation(tmp_path):
    mgr = parallel.SharedCheckpointManager(str(tmp_path / 'mgr'),
                                           max_to_keep=2)
    try:
        for step in range(4):
            mgr.save(step, {'w': jnp.full((2,), float(step))})
        steps = mgr.all_steps()
        assert mgr.latest_step() == 3
        assert len(steps) <= 2 and 3 in steps
        out = mgr.restore()
        assert_almost_equal(np.asarray(out['w']), np.full((2,), 3.0))
    finally:
        mgr.close()


def test_block_params_sharded_roundtrip(tmp_path):
    from mxnet_tpu.parallel.checkpoint import (save_params_sharded,
                                               load_params_sharded)
    net = mx.gluon.nn.Dense(8, in_units=4)
    net.initialize()
    before = {k: v.data().asnumpy() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'blk')
    save_params_sharded(path, net)
    # perturb, then restore
    for _, p in net.collect_params().items():
        p.set_data(mx.np.zeros(p.shape))
    load_params_sharded(path, net)
    after = {k: v.data().asnumpy() for k, v in net.collect_params().items()}
    for k in before:
        assert_almost_equal(after[k], before[k])


def test_restore_or_init(tmp_path):
    from mxnet_tpu.parallel.checkpoint import restore_or_init
    mgr = parallel.SharedCheckpointManager(str(tmp_path / 'el'),
                                           max_to_keep=2)
    try:
        state, step = restore_or_init(mgr, lambda: {'w': jnp.zeros(2)})
        assert step == -1 and float(state['w'][0]) == 0.0
        mgr.save(5, {'w': jnp.full((2,), 7.0)})
        state, step = restore_or_init(mgr, lambda: {'w': jnp.zeros(2)})
        assert step == 5
        assert_almost_equal(np.asarray(state['w']), np.full((2,), 7.0))
    finally:
        mgr.close()
