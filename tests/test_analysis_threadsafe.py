"""Thread-safety of the graph-analysis surfaces (mx.analysis).

The graph sanitizer's walker and report objects run concurrently in two
places: ``hybridize(check=True)`` lints inside the compile path from
whichever thread triggers the first compile, and users call
``mx.analysis.lint()`` from their own threads. These tests barrier-sync
N threads through both entry points — under the dynamic race checker
when enabled — proving the walker/report machinery and the profiler's
report registry tolerate concurrent use.
"""
import threading
import warnings

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.analysis import race


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation='relu'),
            gluon.nn.Dense(4))
    return net


def _run_threads(n, target):
    barrier = threading.Barrier(n)
    errors = []

    def wrap(i):
        try:
            barrier.wait(timeout=30)
            target(i)
        except Exception as e:       # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors


@pytest.fixture
def checker():
    was_active = race.enabled()
    race.enable()
    race.reset()
    yield race
    race.reset()
    if not was_active:
        race.disable()


def test_concurrent_lint_same_function(checker):
    """mx.analysis.lint() from 6 barrier-synced threads over the same
    function: each gets its own complete report, no cross-talk."""
    def fn(x):
        return (x * 2 + 1).sum()

    reports = [None] * 6

    def work(i):
        reports[i] = mx.analysis.lint(fn, onp.ones((4, 4), onp.float32))

    _run_threads(6, work)
    for r in reports:
        assert r is not None and r.rules_run
    assert len({len(r.findings) for r in reports}) == 1
    race.assert_clean()


def test_concurrent_lint_distinct_blocks(checker):
    """Per-thread blocks traced + linted concurrently — the walker holds
    no shared mutable state across graphs."""
    def work(i):
        net = _mlp()
        net.initialize()
        r = mx.analysis.lint(net, (2, 8))
        assert r is not None

    _run_threads(4, work)
    race.assert_clean()


def test_concurrent_hybridize_check_single_block(checker):
    """One shared block, hybridize(check=True), first forward raced by 6
    threads: exactly one wins the compile+lint (under the graph lock),
    everyone gets correct outputs, and the attached profiler report is
    consistent."""
    net = _mlp()
    net.initialize()
    x = mx.np.ones((2, 8))
    net(x)                           # init params single-threaded
    net.hybridize(check=True)
    want = None
    results = [None] * 6

    def work(i):
        with warnings.catch_warnings():
            warnings.simplefilter('ignore')
            results[i] = net(mx.np.ones((2, 8))).asnumpy()

    _run_threads(6, work)
    want = results[0]
    for got in results[1:]:
        onp.testing.assert_allclose(got, want, rtol=1e-6)
    race.assert_clean()


def test_concurrent_hybridize_check_many_blocks(checker):
    """Each thread hybridizes and lints its own block while others do
    the same — exercises the profiler's attach_analysis registry under
    contention (guarded by the profiler stats lock)."""
    from mxnet_tpu import profiler

    def work(i):
        net = _mlp()
        net.initialize()
        net.hybridize(check=True)
        with warnings.catch_warnings():
            warnings.simplefilter('ignore')
            y = net(mx.np.ones((2, 8)))
        y.wait_to_read()

    _run_threads(4, work)
    profiler.dumps()                 # renders the registry w/o error
    race.assert_clean()


def test_concurrent_lint_while_inference(checker):
    """Half the threads serve a hybridized block, half lint a function —
    the two analysis surfaces never share unlocked state."""
    net = _mlp()
    net.initialize()
    net.hybridize()
    warm = net(mx.np.ones((2, 8)))
    warm.wait_to_read()

    def fn(x):
        return x @ x.T

    def work(i):
        if i % 2 == 0:
            net(mx.np.ones((2, 8))).wait_to_read()
        else:
            assert mx.analysis.lint(
                fn, onp.ones((3, 3), onp.float32)) is not None

    _run_threads(6, work)
    race.assert_clean()
