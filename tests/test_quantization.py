"""INT8 PTQ (reference src/operator/quantization/ + calibrate.cc +
quantize_graph_pass.cc; python test model: test_quantization.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import quantization
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray.ndarray import NDArray


def test_quantize_dequantize_roundtrip():
    x = mx.np.array(np.random.RandomState(0).uniform(-3, 5, (4, 16))
                    .astype('float32'))
    q, lo, hi = mx.nd.quantize_v2(x)
    assert q.dtype == np.int8
    back = mx.nd.dequantize(q, lo, hi)
    # symmetric int8: max error is one quantization step
    step = max(abs(float(lo.asnumpy())), abs(float(hi.asnumpy()))) / 127
    assert np.max(np.abs(back.asnumpy() - x.asnumpy())) <= step + 1e-6


def test_quantize_with_calib_range():
    x = mx.np.array(np.array([[-10.0, 0.5, 9.0]], dtype='float32'))
    q, lo, hi = mx.nd.quantize_v2(x, min_calib_range=-1.0,
                                  max_calib_range=1.0)
    # out-of-range values saturate
    qn = q.asnumpy()
    assert qn[0, 0] == -127 and qn[0, 2] == 127


def test_requantize():
    acc = mx.np.array(np.array([[1000, -2000, 30000]], dtype='int32'))
    q, lo, hi = mx.nd.requantize(acc, mx.np.array(-40000.0),
                                 mx.np.array(40000.0),
                                 min_calib_range=-10.0,
                                 max_calib_range=10.0)
    assert q.dtype == np.int8


def _collector_for(data):
    c = quantization._HistogramCollector()
    c.collect(data)
    return c


def test_calibration_modes():
    rng = np.random.RandomState(1)
    data = rng.normal(0, 1, 20000).astype('float32')
    data[0] = 40.0  # one huge outlier
    c = _collector_for(data)
    lo_n, hi_n = c.naive()
    assert hi_n == pytest.approx(40.0)
    lo_p, hi_p = c.percentile(99.9)
    assert hi_p < 10.0  # percentile clips the outlier
    lo_e, hi_e = c.entropy()
    assert 0 < hi_e < 40.0  # entropy threshold clips it too


def test_quantized_dense_accuracy():
    rng = np.random.RandomState(2)
    net = nn.Dense(8, in_units=16)
    net.initialize()
    x = mx.np.array(rng.uniform(-1, 1, (32, 16)).astype('float32'))
    ref = net(x).asnumpy()
    qnet = quantization.quantize_net(net, calib_data=[x],
                                     calib_mode='naive')
    assert isinstance(qnet, quantization.QuantizedDense)  # root swap
    out = qnet(x).asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() / scale < 0.05


def test_quantize_hybridized_net():
    rng = np.random.RandomState(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8))
    net.initialize()
    net.hybridize()
    x = mx.np.array(rng.uniform(-1, 1, (4, 8)).astype('float32'))
    ref = net(x).asnumpy()  # warm the compiled cache
    qnet = quantization.quantize_net(net, calib_data=[x],
                                     calib_mode='naive')
    out = qnet(x).asnumpy()
    assert isinstance(list(qnet._children.values())[0],
                      quantization.QuantizedDense)
    assert np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9) < 0.05


def test_quantize_uint8():
    x = mx.np.array(np.array([[0.0, 0.5, 1.0, 2.0]], dtype='float32'))
    q, lo, hi = mx.nd.quantize_v2(x, min_calib_range=0.0,
                                  max_calib_range=1.0, out_type='uint8')
    assert q.dtype == np.uint8
    qn = q.asnumpy()
    assert qn[0, 3] == 255  # saturates
    back = mx.nd.dequantize(q, lo, hi).asnumpy()
    assert abs(back[0, 1] - 0.5) < 1 / 255 + 1e-6
    with pytest.raises(ValueError):
        mx.nd.quantize_v2(x, out_type='int4')


def test_unexercised_layer_stays_float():
    class Gated(mx.gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.main = nn.Dense(4, in_units=4)
            self.aux = nn.Dense(4, in_units=4)  # never called

        def forward(self, x):
            return self.main(x)

    net = Gated()
    net.initialize()
    x = mx.np.ones((2, 4))
    quantization.quantize_net(net, calib_data=[x], calib_mode='naive')
    assert isinstance(net.main, quantization.QuantizedDense)
    assert isinstance(net.aux, nn.Dense)  # left in float, no KeyError


def test_quantize_net_mlp_swaps_layers():
    rng = np.random.RandomState(3)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation='relu', in_units=20))
    net.add(nn.Dense(10, in_units=32))
    net.initialize()
    calib = [mx.np.array(rng.uniform(-1, 1, (16, 20)).astype('float32'))
             for _ in range(4)]
    ref = net(calib[0]).asnumpy()
    quantization.quantize_net(net, calib_data=calib, calib_mode='entropy')
    flat = []

    def walk(b):
        for ch in b._children.values():
            flat.append(ch)
            walk(ch)
    walk(net)
    assert any(isinstance(b, quantization.QuantizedDense) for b in flat)
    out = net(calib[0]).asnumpy()
    assert np.argmax(out, 1).tolist() == np.argmax(ref, 1).tolist() or \
        np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9) < 0.1


def test_quantized_conv_accuracy():
    rng = np.random.RandomState(4)
    x = mx.np.array(rng.uniform(-1, 1, (2, 4, 8, 8)).astype('float32'))
    seq = nn.HybridSequential()
    conv = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=4)
    seq.add(conv)
    seq.initialize()
    ref = seq(x).asnumpy()
    quantization.quantize_net(seq, calib_data=[x], calib_mode='naive')
    assert isinstance(list(seq._children.values())[0],
                      quantization.QuantizedConv2D)
    out = seq(x).asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() / scale < 0.05


def test_exclude_layers():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4))
    net.add(nn.Dense(2, in_units=8))
    net.initialize()
    x = mx.np.ones((2, 4))
    quantization.quantize_net(net, calib_data=[x], calib_mode='naive',
                              exclude_layers=['0'])
    kids = list(net._children.values())
    assert not isinstance(kids[0], quantization.QuantizedDense)
    assert isinstance(kids[1], quantization.QuantizedDense)


def test_percentile_threshold_covers_requested_mass():
    from mxnet_tpu.quantization import _HistogramCollector
    import numpy as onp2
    rng = onp2.random.default_rng(0)
    # heavy boundary bin: uniform plus a spike near the edge
    x = onp2.concatenate([rng.uniform(-1, 1, 10000),
                          onp2.full(500, 0.995)]).astype('float32')
    c = _HistogramCollector(num_bins=201)
    c.collect(x)
    lo, t = c.percentile(99.0)
    inside = ((x >= -t) & (x <= t)).mean()
    assert inside >= 0.99, f'threshold {t} covers only {inside:.4f}'


def test_quantized_activations_are_bf16_by_default():
    """TPU-first int8: inter-layer activations leave in bf16 (half the
    HBM bytes of f32 — an f32-activation int8 net measured SLOWER than
    the bf16 float net on the bandwidth-bound bench device); opt out
    with activation_dtype='float32'."""
    import numpy as onp
    net = nn.Dense(8, in_units=4)
    net.initialize()
    x = mx.np.array(onp.random.default_rng(0).uniform(
        -1, 1, (2, 4)).astype('f'))
    net(x)
    q16 = quantization.quantize_net(net, calib_data=[x],
                                    calib_mode='naive')
    assert str(q16(x).dtype) == 'bfloat16'
    net2 = nn.Dense(8, in_units=4)
    net2.initialize()
    net2(x)
    q32 = quantization.quantize_net(net2, calib_data=[x],
                                    calib_mode='naive',
                                    activation_dtype='float32')
    assert str(q32(x).dtype) == 'float32'
