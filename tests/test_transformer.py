"""Transformer stack tests: Pallas flash attention + BERT model family.

Coverage model (SURVEY §4): numeric checks vs a plain XLA reference for the
kernel (the role of test_operator.py's numeric checks), end-to-end
train-step assertions for the model (the role of tests/python/train/).
"""

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import bert
from mxnet_tpu.ops.pallas.flash_attention import (_reference_attention,
                                                  flash_attention)


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('t,s', [(64, 64), (32, 96)])
def test_flash_kernel_matches_reference(causal, t, s):
    rng = onp.random.default_rng(0)
    import jax.numpy as jnp
    q = jnp.asarray(rng.standard_normal((2, 2, t, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 2, s, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 2, s, 32)), jnp.float32)
    out = flash_attention(q, k, v, causal=causal, interpret=True,
                          block_q=32, block_k=32)
    ref = _reference_attention(
        q.reshape(-1, t, 32), k.reshape(-1, s, 32), v.reshape(-1, s, 32),
        32 ** -0.5, causal).reshape(q.shape)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-5)


def test_flash_attention_op_and_grad():
    rng = onp.random.default_rng(1)
    q = mx.np.array(rng.standard_normal((2, 2, 32, 16)), dtype='float32')
    q.attach_grad()
    with autograd.record():
        out = mx.npx.flash_attention(q, q, q, causal=True)
        loss = (out ** 2).sum()
    loss.backward()
    assert q.grad is not None
    g = q.grad.asnumpy()
    assert onp.isfinite(g).all() and onp.abs(g).sum() > 0


def test_multi_head_attention_flash_path_matches_masked_path():
    rng = onp.random.default_rng(2)
    b, t, e, h = 2, 16, 32, 4
    q = mx.np.array(rng.standard_normal((b, t, e)), dtype='float32')
    k = mx.np.array(rng.standard_normal((b, t, e)), dtype='float32')
    v = mx.np.array(rng.standard_normal((b, t, e)), dtype='float32')
    out_flash = mx.npx.multi_head_attention(q, k, v, h)
    full = mx.np.ones((b, 1, t, t), dtype='bool')
    out_masked = mx.npx.multi_head_attention(q, k, v, h, mask=full)
    onp.testing.assert_allclose(out_flash.asnumpy(), out_masked.asnumpy(),
                                rtol=1e-5, atol=1e-5)


def _tiny_bert(**kw):
    cfg = dict(vocab_size=200, num_layers=2, units=32, hidden_size=64,
               num_heads=4, max_length=32, dropout=0.0)
    cfg.update(kw)
    return bert.get_bert_model('bert_12_768_12', **cfg)


def test_bert_output_shapes():
    net = _tiny_bert()
    net.initialize()
    ids = mx.np.zeros((2, 12), dtype='int32')
    tt = mx.np.zeros((2, 12), dtype='int32')
    seq, pooled, mlm, nsp = net(ids, tt)
    assert seq.shape == (2, 12, 32)
    assert pooled.shape == (2, 32)
    assert mlm.shape == (2, 12, 200)
    assert nsp.shape == (2, 2)


def test_bert_valid_length_masks_padding():
    net = _tiny_bert(use_decoder=False, use_classifier=False)
    net.initialize()
    rng = onp.random.default_rng(3)
    base = rng.integers(1, 200, (1, 10))
    ids_a = mx.np.array(base, dtype='int32')
    # same first 6 tokens, garbage tail
    tail = base.copy()
    tail[0, 6:] = rng.integers(1, 200, 4)
    ids_b = mx.np.array(tail, dtype='int32')
    vl = mx.np.array([6], dtype='int32')
    tt = mx.np.zeros((1, 10), dtype='int32')
    out_a = net(ids_a, tt, vl)[0].asnumpy()
    out_b = net(ids_b, tt, vl)[0].asnumpy()
    # valid positions must not see the padded tail
    onp.testing.assert_allclose(out_a[0, :6], out_b[0, :6],
                                rtol=1e-5, atol=1e-5)


def test_bert_train_step_reduces_loss():
    net = _tiny_bert(use_classifier=False)
    net.initialize()
    rng = onp.random.default_rng(4)
    ids = mx.np.array(rng.integers(0, 200, (4, 12)), dtype='int32')
    tt = mx.np.zeros((4, 12), dtype='int32')
    labels = mx.np.array(rng.integers(0, 200, (4, 12)), dtype='int32')
    trainer = gluon.Trainer(net.collect_params(), 'adam',
                            {'learning_rate': 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(8):
        with autograd.record():
            _, _, mlm = net(ids, tt)
            loss = loss_fn(mlm, labels).mean()
        loss.backward()
        trainer.step(4)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0]


def test_bert_hybridize_matches_eager():
    net = _tiny_bert(use_classifier=False, use_decoder=False)
    net.initialize()
    ids = mx.np.array(onp.arange(24).reshape(2, 12) % 200, dtype='int32')
    tt = mx.np.zeros((2, 12), dtype='int32')
    ref = net(ids, tt)[0].asnumpy()
    net.hybridize()
    net(ids, tt)
    out = net(ids, tt)[0].asnumpy()
    onp.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_bert_hybridized_train_step():
    """Full hybridized train step (the bench.py path) must work."""
    net = _tiny_bert(use_classifier=False)
    net.initialize()
    ids = mx.np.zeros((2, 8), dtype='int32')
    tt = mx.np.zeros((2, 8), dtype='int32')
    net(ids, tt)
    net.hybridize(static_alloc=True)
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    labels = mx.np.zeros((2, 8), dtype='int32')
    for _ in range(2):
        with autograd.record():
            _, _, mlm = net(ids, tt)
            loss = loss_fn(mlm, labels).mean()
        loss.backward()
        trainer.step(2)
    assert onp.isfinite(float(loss.asnumpy()))


def test_bert_large_config():
    cfg = bert._BERT_CONFIGS['bert_24_1024_16']
    assert cfg['num_layers'] == 24 and cfg['units'] == 1024


def test_mha_causal_alignment_consistent_tne_s():
    """Flash and masked branches must agree on causal alignment when T!=S
    (code-review regression: KV-cache decode)."""
    rng = onp.random.default_rng(5)
    b, t, s, e, h = 1, 2, 6, 16, 2
    q = mx.np.array(rng.standard_normal((b, t, e)), dtype='float32')
    k = mx.np.array(rng.standard_normal((b, s, e)), dtype='float32')
    v = mx.np.array(rng.standard_normal((b, s, e)), dtype='float32')
    out_flash = mx.npx.multi_head_attention(q, k, v, h, causal=True)
    full = mx.np.ones((b, 1, t, s), dtype='bool')
    out_masked = mx.npx.multi_head_attention(q, k, v, h, causal=True,
                                             mask=full)
    onp.testing.assert_allclose(out_flash.asnumpy(), out_masked.asnumpy(),
                                rtol=1e-5, atol=1e-5)


def test_symbolblock_from_traced_symbol_with_aux():
    """In-memory SymbolBlock(sym, inputs) must resolve hoisted constants
    (code-review regression)."""
    from mxnet_tpu.gluon import SymbolBlock, nn

    class PosBlock(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            self.table = mx.np.random.uniform(size=(1, 32, 16))

        def forward(self, x):
            return x + self.table

    net = PosBlock()
    x = mx.np.ones((2, 32, 16))
    ref = net(x).asnumpy()
    sym = net._trace_symbol(x)
    blk = SymbolBlock(sym, 'data')
    onp.testing.assert_allclose(blk(x).asnumpy(), ref, rtol=1e-6)


def test_symbol_unique_positional_flags():
    x = mx.sym.var('x')
    u = mx.sym.np.unique(x, True)
    assert u.num_outputs == 2


def test_flash_causal_more_queries_than_keys_matches_reference():
    """Code-review regression: T > S causal must agree with the XLA path."""
    import jax.numpy as jnp
    rng = onp.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((1, 1, 4, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 1, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 1, 2, 8)), jnp.float32)
    out = flash_attention(q, k, v, causal=True, interpret=True,
                          block_q=2, block_k=2)
    ref = _reference_attention(q.reshape(-1, 4, 8), k.reshape(-1, 2, 8),
                               v.reshape(-1, 2, 8), 8 ** -0.5,
                               True).reshape(q.shape)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-5)


def test_mha_dropout_requires_key_and_masks():
    rng = onp.random.default_rng(8)
    x = mx.np.array(rng.standard_normal((2, 8, 16)), dtype='float32')
    with pytest.raises(ValueError, match='key'):
        mx.npx.multi_head_attention(x, x, x, 4, dropout_p=0.5)
    import jax
    out = mx.npx.multi_head_attention(x, x, x, 4, dropout_p=0.5,
                                      key=jax.random.PRNGKey(0))
    assert out.shape == (2, 8, 16)
    base = mx.npx.multi_head_attention(x, x, x, 4)
    assert abs(out.asnumpy() - base.asnumpy()).max() > 1e-4  # masked


def test_bert_classifier_requires_pooler():
    with pytest.raises(ValueError, match='use_pooler'):
        bert.BERTModel(vocab_size=10, units=8, hidden_size=16,
                       num_layers=1, num_heads=2, use_pooler=False,
                       use_classifier=True)


def test_bert_hf_weight_import_matches_transformers():
    """Cross-implementation parity for BERT: logits from an HF
    BertForPreTraining's random weights must match ours."""
    torch = pytest.importorskip('torch')
    transformers = pytest.importorskip('transformers')

    hf_cfg = transformers.BertConfig(
        vocab_size=120, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=96,
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, hidden_act='gelu',
        attn_implementation='eager')
    torch.manual_seed(0)
    hf = transformers.BertForPreTraining(hf_cfg).eval()

    net = bert.BERTModel(vocab_size=120, units=48, hidden_size=96,
                         num_layers=2, num_heads=4, max_length=32,
                         dropout=0.0)
    net.initialize()
    toks = onp.array([[2, 45, 99, 7, 3]], 'f')
    segs = onp.array([[0, 0, 1, 1, 1]], 'f')
    net(mx.np.array(toks), mx.np.array(segs))
    bert.load_hf_state_dict(net, hf.state_dict())

    seq, pooled, mlm, nsp = net(mx.np.array(toks), mx.np.array(segs))
    with torch.no_grad():
        out = hf(torch.tensor(toks.astype('i8')),
                 token_type_ids=torch.tensor(segs.astype('i8')))
    err_mlm = onp.abs(mlm.asnumpy() -
                     out.prediction_logits.numpy()).max()
    err_nsp = onp.abs(nsp.asnumpy() -
                     out.seq_relationship_logits.numpy()).max()
    assert err_mlm < 5e-3, f'MLM logit mismatch {err_mlm}'
    assert err_nsp < 5e-3, f'NSP logit mismatch {err_nsp}'


def test_sliding_window_attention_matches_dense_band():
    """sldwin ops equal full attention under an explicit band mask."""
    B, S, H, D, w = 2, 8, 2, 4, 2
    rng = onp.random.default_rng(0)
    q = mx.np.array(rng.standard_normal((B, S, H, D), dtype='f'))
    k = mx.np.array(rng.standard_normal((B, S, H, D), dtype='f'))
    v = mx.np.array(rng.standard_normal((B, S, H, D), dtype='f'))

    score = mx.npx.sldwin_atten_score(q, k, 1, w)
    probs = mx.npx.softmax(score * (D ** -0.5), axis=-1)
    out = mx.npx.sldwin_atten_context(probs, v, 1, w)
    assert out.shape == (B, S, H, D)

    # dense reference with the same band
    qn, kn, vn = (t.asnumpy() for t in (q, k, v))
    s = onp.einsum('bqhd,bkhd->bhqk', qn, kn) * (D ** -0.5)
    i = onp.arange(S)[:, None]
    j = onp.arange(S)[None, :]
    band = (onp.abs(i - j) <= w)[None, None]
    s = onp.where(band, s, -1e30)
    e = onp.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    want = onp.einsum('bhqk,bkhd->bqhd', p, vn)
    onp.testing.assert_allclose(out.asnumpy(), want, rtol=1e-4, atol=1e-5)

    # mask_like: band ∩ valid_length
    m = mx.npx.sldwin_atten_mask_like(mx.np.array(s.astype('f')), 1,
                                      mx.np.array(onp.array([8, 5], 'f')),
                                      w)
    mn = m.asnumpy()
    assert mn[0].astype(bool).sum() == band[0, 0].sum() * 2  # both heads
    assert not mn[1, 0, 6:, :].any()          # beyond valid_length 5


def test_flash_stats_merge_equals_single_shot():
    """flash_attention_stats blocks merged with _merge_stats must equal
    full softmax attention — the ring-attention correctness core."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.flash_attention import (
        flash_attention_stats, _reference_attention)
    from mxnet_tpu.parallel.ring_attention import _merge_stats

    rng = onp.random.default_rng(0)
    bh, t, d = 2, 8, 4
    q = jnp.asarray(rng.standard_normal((bh, t, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((bh, 2 * t, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((bh, 2 * t, d)), jnp.float32)
    scale = d ** -0.5

    # two key blocks computed independently, then merged
    acc1, m1, l1 = flash_attention_stats(q, k[:, :t], v[:, :t], scale,
                                         interpret=True)
    acc2, m2, l2 = flash_attention_stats(q, k[:, t:], v[:, t:], scale,
                                         interpret=True)
    m0 = jnp.full((bh, t), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bh, t), jnp.float32)
    o0 = jnp.zeros((bh, t, d), jnp.float32)
    m, l, o = _merge_stats(m0, l0, o0, acc1, m1, l1)
    m, l, o = _merge_stats(m, l, o, acc2, m2, l2)
    out = o / jnp.maximum(l[..., None], 1e-30)
    ref = _reference_attention(q, k, v, scale, causal=False)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-5, atol=2e-5)


def test_flash_stats_causal_diagonal():
    """Diagonal-block causal stats (q_pos >= k_pos, same shard) match the
    masked reference."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.flash_attention import (
        flash_attention_stats, _reference_attention)

    rng = onp.random.default_rng(1)
    bh, t, d = 2, 8, 4
    q = jnp.asarray(rng.standard_normal((bh, t, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((bh, t, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((bh, t, d)), jnp.float32)
    scale = d ** -0.5
    acc, m, l = flash_attention_stats(q, k, v, scale, causal=True,
                                      interpret=True)
    out = acc / jnp.maximum(l[..., None], 1e-30)
    ref = _reference_attention(q, k, v, scale, causal=True)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-5, atol=2e-5)
