"""Trainer (reference tests/python/unittest/test_gluon_trainer.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def _make_net():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    return net


def test_trainer_basic_step():
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1})
    w0 = net.weight.data().asnumpy().copy()
    x = mx.np.ones((4, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(4)
    assert not np.allclose(net.weight.data().asnumpy(), w0)


def test_trainer_learning_rate():
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1})
    assert trainer.learning_rate == pytest.approx(0.1)
    trainer.set_learning_rate(0.2)
    assert trainer.learning_rate == pytest.approx(0.2)


def test_linear_regression_convergence():
    np.random.seed(3)
    true_w = np.array([[2.0], [-3.4]], dtype='float32')
    true_b = 4.2
    X = np.random.randn(256, 2).astype('float32')
    Y = (X @ true_w).ravel() + true_b
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1})
    loss_fn = gluon.loss.L2Loss()
    data, label = mx.np.array(X), mx.np.array(Y)
    for _ in range(150):
        with autograd.record():
            l = loss_fn(net(data), label).mean()
        l.backward()
        trainer.step(1)
    assert float(l.asnumpy()) < 1e-3
    assert_almost_equal(net.weight.data().asnumpy().ravel(),
                        true_w.ravel(), rtol=0.05, atol=0.02)
    assert abs(float(net.bias.data().asnumpy()) - true_b) < 0.05


def test_trainer_states_roundtrip(tmp_path):
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), 'adam')
    x = mx.np.ones((2, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(2)
    f = str(tmp_path / 'trainer.states')
    trainer.save_states(f)
    trainer2 = gluon.Trainer(net.collect_params(), 'adam')
    trainer2.load_states(f)
    assert trainer2._optimizer.num_update == trainer._optimizer.num_update


def test_trainer_states_roundtrip_bit_identical_next_update(tmp_path):
    """load_states must restore EVERYTHING the next update depends on —
    adam slots, the global update counter, per-param counts, and the
    lr-scheduler's mutable state — so the restored trainer's next step
    is bit-identical to the original's (the elastic-resume contract;
    a lost num_update would silently reset adam bias correction and the
    lr schedule)."""
    def build():
        net = nn.Dense(2, in_units=3)
        net.initialize()
        sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5,
                                                base_lr=0.1)
        trainer = gluon.Trainer(net.collect_params(), 'adam',
                                {'learning_rate': 0.1,
                                 'lr_scheduler': sched})
        return net, trainer

    def step(net, trainer, s):
        x = mx.np.array(np.full((2, 3), 0.5 + s, dtype='float32'))
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        trainer.step(2)

    net1, tr1 = build()
    for s in range(4):                       # crosses a scheduler factor
        step(net1, tr1, s)
    f = str(tmp_path / 'tr.states')
    tr1.save_states(f)
    w_ckpt = {k: v.data().asnumpy().copy()
              for k, v in net1.collect_params().items()}

    net2, tr2 = build()
    for k, p in net2.collect_params().items():
        p.set_data(mx.np.array(w_ckpt[k]))
    tr2.load_states(f)
    assert tr2._optimizer.num_update == tr1._optimizer.num_update
    sch1 = tr1._optimizer.lr_scheduler
    sch2 = tr2._optimizer.lr_scheduler
    assert sch2.count == sch1.count
    assert sch2.base_lr == pytest.approx(sch1.base_lr)

    step(net1, tr1, 4)
    step(net2, tr2, 4)
    for k in w_ckpt:
        a = net1.collect_params()[k].data().asnumpy()
        b = net2.collect_params()[k].data().asnumpy()
        assert a.tobytes() == b.tobytes(), k


def test_trainer_load_states_accepts_legacy_tuple(tmp_path):
    """Pre-elastic state files pickled (states, num_update) — they must
    still load."""
    import pickle
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), 'adam')
    x = mx.np.ones((2, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(2)
    sd = trainer.state_dict()
    f = str(tmp_path / 'legacy.states')
    with open(f, 'wb') as fh:
        pickle.dump((sd['states'], sd['num_update']), fh)
    trainer2 = gluon.Trainer(net.collect_params(), 'adam')
    trainer2.load_states(f)
    assert trainer2._optimizer.num_update == trainer._optimizer.num_update


def test_trainer_with_kvstore_types():
    for kv in ('local', 'device', 'dist_sync'):
        net = _make_net()
        trainer = gluon.Trainer(net.collect_params(), 'sgd',
                                {'learning_rate': 0.01}, kvstore=kv)
        x = mx.np.ones((2, 2))
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        trainer.step(2)


def test_trainer_update_on_kvstore():
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1}, kvstore='local',
                            update_on_kvstore=True)
    x = mx.np.ones((2, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    w0 = net.weight.data().asnumpy().copy()
    trainer.step(2)
    assert not np.allclose(net.weight.data().asnumpy(), w0)


def test_trainer_allreduce_and_update_split():
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1})
    x = mx.np.ones((2, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.allreduce_grads()
    trainer.update(2)


def test_bf16_cast_net_keeps_dtype_across_steps():
    """A bf16-cast net must still be bf16 after trainer.step — round-2
    regression: momentum math promoted weights to f32 after step 1,
    breaking the cached graph's dtype signature."""
    net = mx.gluon.nn.Dense(4, in_units=3)
    net.initialize()
    net(mx.np.ones((1, 3)))
    net.cast('bfloat16')
    trainer = mx.gluon.Trainer(net.collect_params(), 'sgd',
                               {'learning_rate': 0.1, 'momentum': 0.9})
    x = mx.np.ones((2, 3), dtype='bfloat16')
    from mxnet_tpu import autograd
    for _ in range(3):
        with autograd.record():
            loss = (net(x).astype('float32') ** 2).sum()
        loss.backward()
        trainer.step(2)
    assert str(net.weight.data().dtype) == 'bfloat16'
    # per-param (non-fused) path too
    net2 = mx.gluon.nn.Dense(4, in_units=3)
    net2.initialize()
    net2(mx.np.ones((1, 3)))
    net2.cast('bfloat16')
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    state = opt.create_state(0, net2.weight.data())
    g = mx.np.ones(net2.weight.shape, dtype='bfloat16')
    opt.update(0, net2.weight.data(), g, state)
    assert str(net2.weight.data().dtype) == 'bfloat16'
