"""ONNX export/import round-trip (reference python/mxnet/contrib/onnx).

The ONNX IR protobuf is vendored with spec field numbers, so these tests
validate real .onnx wire format without the onnx package."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.test_utils import assert_almost_equal


def _convnet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, padding=1), gluon.nn.BatchNorm(),
            gluon.nn.Activation('relu'), gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(), gluon.nn.Dense(10))
    net.initialize()
    return net


def test_export_import_convnet_roundtrip(tmp_path):
    net = _convnet()
    x = mx.np.array(np.random.uniform(-1, 1, (2, 2, 8, 8)).astype('f'))
    want = net(x).asnumpy()

    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'model.onnx')
    out = mx.contrib.onnx.export_model(sym, params,
                                       input_shapes=[(2, 2, 8, 8)],
                                       onnx_file_path=path)
    assert out == path

    sym2, arg_params, aux = mx.contrib.onnx.import_model(path)
    bindings = dict(arg_params)
    bindings['data'] = x
    got = sym2.eval(**bindings)[0].asnumpy()
    assert_almost_equal(got, want, rtol=1e-4, atol=1e-5)


def test_export_import_mlp_gelu_layernorm(tmp_path):
    class MLP(gluon.nn.HybridSequential):
        pass

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16), gluon.nn.GELU(), gluon.nn.LayerNorm(),
            gluon.nn.Dense(4))
    net.initialize()
    x = mx.np.array(np.random.uniform(-1, 1, (3, 8)).astype('f'))
    want = net(x).asnumpy()

    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'mlp.onnx')
    mx.contrib.onnx.export_model(sym, params, input_shapes=[(3, 8)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    got = sym2.eval(data=x, **arg_params)[0].asnumpy()
    assert_almost_equal(got, want, rtol=1e-4, atol=1e-5)


def test_exported_file_is_valid_onnx_wire_format(tmp_path):
    """Check header fields parse from the raw bytes (wire compat)."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4))
    net.initialize()
    x = mx.np.ones((1, 3))
    net(x)
    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'd.onnx')
    mx.contrib.onnx.export_model(sym, params, input_shapes=[(1, 3)],
                                 onnx_file_path=path)
    from mxnet_tpu.contrib.onnx import onnx_ir_pb2 as pb
    m = pb.ModelProto()
    m.ParseFromString(open(path, 'rb').read())
    assert m.producer_name == 'mxnet_tpu'
    assert m.opset_import[0].version == 17
    assert len(m.graph.node) >= 1
    assert m.graph.node[-1].op_type in ('Gemm', 'MatMul')
    assert m.graph.input[0].type.tensor_type.shape.dim[1].dim_value == 3


def test_embedding_and_elemwise_export(tmp_path):
    emb = gluon.nn.Embedding(10, 6)
    emb.initialize()
    idx = mx.np.array(np.array([[1, 2], [3, 4]], 'f'))
    want = (emb(idx) * 2.0).asnumpy()

    class Net(gluon.nn.HybridSequential):
        def forward(self, x):
            return emb(x) * 2.0

    net = Net()
    sym = net._trace_symbol(idx)
    params = {k: v.data() for k, v in emb.collect_params().items()}
    path = str(tmp_path / 'e.onnx')
    mx.contrib.onnx.export_model(sym, params, input_shapes=[(2, 2)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    got = sym2.eval(data=idx, **arg_params)[0].asnumpy()
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('name', ['mobilenet_v2_0_25', 'squeezenet1_0'])
def test_vision_zoo_roundtrip(tmp_path, name):
    """Model-zoo nets export and reimport with identical outputs (the
    relu6/concatenate/clip converter coverage)."""
    from mxnet_tpu.gluon.model_zoo import vision
    net = getattr(vision, name)()
    net.initialize()
    x = mx.np.array(np.random.uniform(-1, 1, (1, 3, 224, 224)).astype('f'))
    want = net(x).asnumpy()
    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / f'{name}.onnx')
    mx.contrib.onnx.export_model(sym, params,
                                 input_shapes=[(1, 3, 224, 224)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    got = sym2.eval(data=x, **arg_params)[0].asnumpy()
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-5)


def test_stochastic_op_under_abstract_eval_does_not_leak_tracers(tmp_path):
    """Regression: exporting a net with Dropout (stochastic op) must not
    poison the global RNG with traced keys (mx2onnx._infer_outputs runs
    the symbol under jax.eval_shape)."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8), gluon.nn.Dropout(0.5), gluon.nn.Dense(2))
    net.initialize()
    x = mx.np.ones((1, 4))
    net(x)
    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    mx.contrib.onnx.export_model(sym, params, input_shapes=[(1, 4)],
                                 onnx_file_path=str(tmp_path / 'd.onnx'))
    # eager RNG still healthy after the abstract eval
    out = mx.np.random.uniform(0, 1, (3,))
    assert np.isfinite(out.asnumpy()).all()


def test_bert_encoder_onnx_roundtrip(tmp_path):
    """The transformer stack exports: fused attention decomposes into
    MatMul/Softmax primitives, qkv split and CLS-token slicing convert."""
    from mxnet_tpu.gluon.model_zoo import bert
    net = bert.get_bert_model(num_layers=2, vocab_size=100, units=32,
                              hidden_size=64, num_heads=2, dropout=0.0,
                              use_decoder=False, use_classifier=False)
    net.initialize()
    toks = mx.np.array(np.random.randint(1, 100, (2, 6)).astype('f'))
    segs = mx.np.zeros((2, 6))
    seq, pooled = net(toks, segs)

    sym = net._trace_symbol(toks, segs)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'bert.onnx')
    mx.contrib.onnx.export_model(sym, params,
                                 input_shapes=[(2, 6), (2, 6)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    bindings = dict(arg_params)
    names = [n for n in sym2.list_arguments() if n not in arg_params]
    got = sym2.eval(**bindings, **dict(zip(sorted(names),
                                           [toks, segs])))
    assert_almost_equal(got[0].asnumpy(), seq.asnumpy(),
                        rtol=1e-4, atol=1e-4)
    assert_almost_equal(got[1].asnumpy(), pooled.asnumpy(),
                        rtol=1e-4, atol=1e-4)
