"""ONNX export/import round-trip (reference python/mxnet/contrib/onnx).

The ONNX IR protobuf is vendored with spec field numbers, so these tests
validate real .onnx wire format without the onnx package."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.test_utils import assert_almost_equal


def _convnet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, padding=1), gluon.nn.BatchNorm(),
            gluon.nn.Activation('relu'), gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(), gluon.nn.Dense(10))
    net.initialize()
    return net


def test_export_import_convnet_roundtrip(tmp_path):
    net = _convnet()
    x = mx.np.array(np.random.uniform(-1, 1, (2, 2, 8, 8)).astype('f'))
    want = net(x).asnumpy()

    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'model.onnx')
    out = mx.contrib.onnx.export_model(sym, params,
                                       input_shapes=[(2, 2, 8, 8)],
                                       onnx_file_path=path)
    assert out == path

    sym2, arg_params, aux = mx.contrib.onnx.import_model(path)
    bindings = dict(arg_params)
    bindings['data'] = x
    got = sym2.eval(**bindings)[0].asnumpy()
    assert_almost_equal(got, want, rtol=1e-4, atol=1e-5)


def test_export_import_mlp_gelu_layernorm(tmp_path):
    class MLP(gluon.nn.HybridSequential):
        pass

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16), gluon.nn.GELU(), gluon.nn.LayerNorm(),
            gluon.nn.Dense(4))
    net.initialize()
    x = mx.np.array(np.random.uniform(-1, 1, (3, 8)).astype('f'))
    want = net(x).asnumpy()

    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'mlp.onnx')
    mx.contrib.onnx.export_model(sym, params, input_shapes=[(3, 8)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    got = sym2.eval(data=x, **arg_params)[0].asnumpy()
    assert_almost_equal(got, want, rtol=1e-4, atol=1e-5)


def test_exported_file_is_valid_onnx_wire_format(tmp_path):
    """Check header fields parse from the raw bytes (wire compat)."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4))
    net.initialize()
    x = mx.np.ones((1, 3))
    net(x)
    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'd.onnx')
    mx.contrib.onnx.export_model(sym, params, input_shapes=[(1, 3)],
                                 onnx_file_path=path)
    from mxnet_tpu.contrib.onnx import onnx_ir_pb2 as pb
    m = pb.ModelProto()
    m.ParseFromString(open(path, 'rb').read())
    assert m.producer_name == 'mxnet_tpu'
    assert m.opset_import[0].version == 17
    assert len(m.graph.node) >= 1
    assert m.graph.node[-1].op_type in ('Gemm', 'MatMul')
    assert m.graph.input[0].type.tensor_type.shape.dim[1].dim_value == 3


def test_embedding_and_elemwise_export(tmp_path):
    emb = gluon.nn.Embedding(10, 6)
    emb.initialize()
    idx = mx.np.array(np.array([[1, 2], [3, 4]], 'f'))
    want = (emb(idx) * 2.0).asnumpy()

    class Net(gluon.nn.HybridSequential):
        def forward(self, x):
            return emb(x) * 2.0

    net = Net()
    sym = net._trace_symbol(idx)
    params = {k: v.data() for k, v in emb.collect_params().items()}
    path = str(tmp_path / 'e.onnx')
    mx.contrib.onnx.export_model(sym, params, input_shapes=[(2, 2)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    got = sym2.eval(data=idx, **arg_params)[0].asnumpy()
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('name', ['mobilenet_v2_0_25', 'squeezenet1_0'])
def test_vision_zoo_roundtrip(tmp_path, name):
    """Model-zoo nets export and reimport with identical outputs (the
    relu6/concatenate/clip converter coverage)."""
    from mxnet_tpu.gluon.model_zoo import vision
    net = getattr(vision, name)()
    net.initialize()
    x = mx.np.array(np.random.uniform(-1, 1, (1, 3, 224, 224)).astype('f'))
    want = net(x).asnumpy()
    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / f'{name}.onnx')
    mx.contrib.onnx.export_model(sym, params,
                                 input_shapes=[(1, 3, 224, 224)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    got = sym2.eval(data=x, **arg_params)[0].asnumpy()
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-5)


def test_stochastic_op_under_abstract_eval_does_not_leak_tracers(tmp_path):
    """Regression: exporting a net with Dropout (stochastic op) must not
    poison the global RNG with traced keys (mx2onnx._infer_outputs runs
    the symbol under jax.eval_shape)."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8), gluon.nn.Dropout(0.5), gluon.nn.Dense(2))
    net.initialize()
    x = mx.np.ones((1, 4))
    net(x)
    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    mx.contrib.onnx.export_model(sym, params, input_shapes=[(1, 4)],
                                 onnx_file_path=str(tmp_path / 'd.onnx'))
    # eager RNG still healthy after the abstract eval
    out = mx.np.random.uniform(0, 1, (3,))
    assert np.isfinite(out.asnumpy()).all()


def test_bert_encoder_onnx_roundtrip(tmp_path):
    """The transformer stack exports: fused attention decomposes into
    MatMul/Softmax primitives, qkv split and CLS-token slicing convert."""
    from mxnet_tpu.gluon.model_zoo import bert
    net = bert.get_bert_model(num_layers=2, vocab_size=100, units=32,
                              hidden_size=64, num_heads=2, dropout=0.0,
                              use_decoder=False, use_classifier=False)
    net.initialize()
    toks = mx.np.array(np.random.randint(1, 100, (2, 6)).astype('f'))
    segs = mx.np.zeros((2, 6))
    seq, pooled = net(toks, segs)

    sym = net._trace_symbol(toks, segs)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'bert.onnx')
    mx.contrib.onnx.export_model(sym, params,
                                 input_shapes=[(2, 6), (2, 6)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    bindings = dict(arg_params)
    names = [n for n in sym2.list_arguments() if n not in arg_params]
    got = sym2.eval(**bindings, **dict(zip(sorted(names),
                                           [toks, segs])))
    assert_almost_equal(got[0].asnumpy(), seq.asnumpy(),
                        rtol=1e-4, atol=1e-4)
    assert_almost_equal(got[1].asnumpy(), pooled.asnumpy(),
                        rtol=1e-4, atol=1e-4)


def test_bert_base_dims_onnx_logit_parity(tmp_path):
    """BERT-base architecture (12 layers, 768 units, 12 heads, 3072
    hidden) export -> import -> logit parity (VERDICT r1 item 9; vocab
    kept small so the artifact stays CI-sized — the graph structure is
    the full base config)."""
    from mxnet_tpu.gluon.model_zoo import bert
    net = bert.get_bert_model(num_layers=12, vocab_size=2000, units=768,
                              hidden_size=3072, num_heads=12,
                              dropout=0.0, use_decoder=False,
                              use_classifier=False)
    net.initialize()
    toks = mx.np.array(np.random.randint(1, 2000, (2, 16)).astype('f'))
    segs = mx.np.zeros((2, 16))
    seq, pooled = net(toks, segs)

    sym = net._trace_symbol(toks, segs)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'bert_base.onnx')
    mx.contrib.onnx.export_model(sym, params,
                                 input_shapes=[(2, 16), (2, 16)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    bindings = dict(arg_params)
    names = [n for n in sym2.list_arguments() if n not in arg_params]
    got = sym2.eval(**bindings, **dict(zip(sorted(names), [toks, segs])))
    assert_almost_equal(got[0].asnumpy(), seq.asnumpy(),
                        rtol=1e-3, atol=1e-4)
    assert_almost_equal(got[1].asnumpy(), pooled.asnumpy(),
                        rtol=1e-3, atol=1e-4)


def test_causal_attention_onnx_roundtrip(tmp_path):
    """Decoder-style causal attention exports (additive triangular mask
    before the softmax) and round-trips."""
    from mxnet_tpu import gluon

    class CausalSelfAtt(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.qkv = gluon.nn.Dense(3 * 32, in_units=32, flatten=False)

        def forward(self, x):
            q, k, v = mx.np.split(self.qkv(x), 3, axis=-1)
            return mx.npx.multi_head_attention(q, k, v, num_heads=4,
                                               causal=True)

    net = CausalSelfAtt()
    net.initialize()
    x = mx.np.array(np.random.randn(2, 6, 32).astype('f'))
    want = net(x)
    sym = net._trace_symbol(x)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'causal.onnx')
    mx.contrib.onnx.export_model(sym, params, input_shapes=[(2, 6, 32)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    names = [n for n in sym2.list_arguments() if n not in arg_params]
    got = sym2.eval(**dict(arg_params), **{names[0]: x})
    got = got[0] if isinstance(got, (list, tuple)) else got
    assert_almost_equal(got.asnumpy(), want.asnumpy(), rtol=1e-4,
                        atol=1e-5)
    # causality check on the imported graph: future tokens don't matter
    x2 = mx.np.array(np.concatenate(
        [x.asnumpy()[:, :3], np.random.randn(2, 3, 32).astype('f')], 1))
    got2 = sym2.eval(**dict(arg_params), **{names[0]: x2})
    got2 = got2[0] if isinstance(got2, (list, tuple)) else got2
    assert_almost_equal(got2.asnumpy()[:, :3], want.asnumpy()[:, :3],
                        rtol=1e-4, atol=1e-5)


def test_strided_slice_and_unequal_split_roundtrip(tmp_path):
    from mxnet_tpu import gluon

    class Net(gluon.HybridBlock):
        def forward(self, x):
            a = x[:, ::2]                      # strided
            b_ = x[:, ::-1]                    # negative stride
            c, d = mx.np.split(x, [3], axis=1)  # unequal split (3, 5)
            red = lambda t: t.sum(-1).sum(-1, keepdims=True)
            return red(a) + red(b_) * 0.5 + red(c) + red(d)

    net = Net()
    net.initialize()
    x = mx.np.array(np.random.randn(2, 8, 4).astype('f'))
    want = net(x)
    sym = net._trace_symbol(x)
    path = str(tmp_path / 'strided.onnx')
    mx.contrib.onnx.export_model(sym, {}, input_shapes=[(2, 8, 4)],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    names = [n for n in sym2.list_arguments() if n not in arg_params]
    got = sym2.eval(**dict(arg_params), **{names[0]: x})
    got = got[0] if isinstance(got, (list, tuple)) else got
    assert_almost_equal(got.asnumpy(), want.asnumpy(), rtol=1e-5,
                        atol=1e-6)


def test_masked_attention_kwarg_roundtrip(tmp_path):
    """A keyword-passed boolean mask must reach the exported graph
    (round-2 review regression: it was silently dropped)."""
    from mxnet_tpu import gluon

    class MaskedAtt(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.qkv = gluon.nn.Dense(3 * 16, in_units=16, flatten=False)

        def forward(self, x, mask):
            q, k, v = mx.np.split(self.qkv(x), 3, axis=-1)
            return mx.npx.multi_head_attention(q, k, v, num_heads=2,
                                               mask=mask)

    net = MaskedAtt()
    net.initialize()
    x = mx.np.array(np.random.randn(1, 4, 16).astype('f'))
    m = mx.np.array(np.tril(np.ones((1, 1, 4, 4))).astype(bool))
    want = net(x, m)
    sym = net._trace_symbol(x, m)
    params = {k: v.data() for k, v in net.collect_params().items()}
    path = str(tmp_path / 'masked.onnx')
    mx.contrib.onnx.export_model(sym, params,
                                 input_shapes=[(1, 4, 16), (1, 1, 4, 4)],
                                 input_types=['float32', 'bool'],
                                 onnx_file_path=path)
    sym2, arg_params, _ = mx.contrib.onnx.import_model(path)
    names = sorted(n for n in sym2.list_arguments() if n not in arg_params)
    got = sym2.eval(**dict(arg_params), **dict(zip(names, [x, m])))
    got = got[0] if isinstance(got, (list, tuple)) else got
    assert_almost_equal(got.asnumpy(), want.asnumpy(), rtol=1e-4,
                        atol=1e-5)
    # the mask must actually matter in the imported graph
    m2 = mx.np.array(np.ones((1, 1, 4, 4)).astype(bool))
    got2 = sym2.eval(**dict(arg_params), **dict(zip(names, [x, m2])))
    got2 = got2[0] if isinstance(got2, (list, tuple)) else got2
    assert np.abs(got2.asnumpy() - want.asnumpy()).max() > 1e-4
