"""Mesh-scoped sharding context: ``with mx.sharding.mesh(dp=4, tp=2):``.

Inside the context every ``HybridBlock.hybridize()`` compile routes
through ``jax.jit`` with ``in_shardings`` derived from the partition-rule
registry (rules.py), parameters are placed sharded on the mesh, the
Trainer partitions optimizer slots along the data axis (ZeRO-1), and
``DecodeServer`` shards its KV page pool — all with zero model-code
changes (gluon/block.py reads the ambient context at compile time).

The context is thread-local and reentrant (a stack); its
``fingerprint()`` is part of the ``_CachedGraph`` compile-cache key, so
entering a *different* mesh retraces by design (a new device assignment
is a new XLA program — the recompile-hazard rule documents this as a
non-hazard), while re-entering the *same* mesh shape hits the warm
cache.

Env overrides (docs/env_vars.md):

* ``MXNET_SHARDING_DP`` / ``MXNET_SHARDING_TP`` — override the axis
  sizes passed to :func:`mesh` (deploy-time reshape without code edits);
* ``MXNET_SHARDING_DISABLE=1`` — make :func:`mesh` a no-op (escape
  hatch: single-device semantics for bisection);
* ``MXNET_SHARDING_STRICT=1`` — error instead of replicating when a
  rule's mesh axis does not divide the dim (rules.resolve_spec).
"""

import os
import threading
from contextlib import contextmanager

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import rules as _rules

__all__ = ['ShardingContext', 'MeshGroup', 'mesh', 'current',
           'constrain', 'batch_spec', 'use']

_STACK = threading.local()


def _stack():
    if not hasattr(_STACK, 'items'):
        _STACK.items = []
    return _STACK.items


def current():
    """The innermost active :class:`ShardingContext`, or None."""
    items = _stack()
    return items[-1] if items else None


class ShardingContext:
    """One mesh + rule table + the derived placement helpers."""

    def __init__(self, mesh, rules=None, mode=None, arch=None,
                 data_axis='dp'):
        self.mesh = mesh
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self.axis_sizes = sizes
        if mode is None:
            mode = 'tp' if sizes.get('tp', 1) > 1 else 'fsdp'
        self.mode = mode
        self.arch = arch          # None -> inferred per block
        self._rules = rules       # explicit table beats the registry
        self.data_axis = data_axis if sizes.get(data_axis, 1) > 1 else None
        self.n_devices = int(mesh.devices.size)

    # ------------------------------------------------------------- identity
    def fingerprint(self):
        """Hashable identity for compile-cache keys: mesh shape + axis
        names + device ids + mode (+ rule-table identity). Two contexts
        over the same devices/axes/rules share compiled executables."""
        dev_ids = tuple(int(d.id) for d in self.mesh.devices.flat)
        return (tuple(self.mesh.axis_names),
                tuple(self.mesh.devices.shape), dev_ids, self.mode,
                self.arch, id(self._rules) if self._rules else None)

    # ------------------------------------------------------------ rule match
    def rules_for_block(self, block=None, arch=None):
        if self._rules is not None:
            return self._rules
        arch = arch or self.arch
        if arch is None and block is not None:
            arch = _rules.infer_arch(block)
        arch = arch or 'generic'
        try:
            return _rules.rules_for(arch, self.mode)
        except KeyError:
            if arch != 'generic' and self.mode == 'fsdp':
                return _rules.rules_for('generic', 'fsdp')
            raise

    def spec_for(self, name, shape, rules):
        """Resolved PartitionSpec for one named parameter (rule match +
        divisibility fallback against this mesh)."""
        spec = _rules.match_spec(name, shape, rules)
        return _rules.resolve_spec(spec, shape, self.mesh, name=name)

    def sharding_for(self, name, shape, rules):
        return NamedSharding(self.mesh, self.spec_for(name, shape, rules))

    # ------------------------------------------------------------ placement
    def batch_spec(self, shape):
        """Activation spec: leading (batch) dim on the data axis when it
        divides, otherwise replicated — the rule-tagged graph boundary
        the hybridize cache constrains activations at."""
        if self.data_axis is None or not shape:
            return P()
        extent = self.axis_sizes.get(self.data_axis, 1)
        if shape[0] % extent:
            return P()
        return P(self.data_axis)

    def put(self, raw, spec):
        return jax.device_put(raw, NamedSharding(self.mesh, spec))

    def zero1_spec(self, param_spec, shape):
        """Optimizer-slot spec: the parameter's layout plus the data
        axis on the first still-replicated divisible dim — optimizer
        state partitioned along 'dp' (ZeRO-1; the GSPMD expression of
        the kvstore/tpu.py ``_zero1_update`` owner plan, where each
        data-parallel rank updates only its slice)."""
        if self.data_axis is None:
            return param_spec
        extent = self.axis_sizes.get(self.data_axis, 1)
        entries = list(tuple(param_spec)) + [None] * (len(shape)
                                                      - len(param_spec))
        used = set()
        for e in entries:
            for a in (e if isinstance(e, tuple) else (e,)):
                if a is not None:
                    used.add(a)
        if self.data_axis in used:
            return param_spec
        sizes = self.axis_sizes
        for d, e in enumerate(entries):
            have = 1
            for a in ((e if isinstance(e, tuple) else (e,)) or ()):
                if a is not None:
                    have *= sizes.get(a, 1)
            if shape[d] % (have * extent) == 0 and shape[d] >= extent:
                if e is None:
                    entries[d] = self.data_axis
                elif isinstance(e, tuple):
                    entries[d] = e + (self.data_axis,)
                else:
                    entries[d] = (e, self.data_axis)
                while entries and entries[-1] is None:
                    entries.pop()
                return P(*entries)
        return param_spec

    def __repr__(self):
        ax = ', '.join(f'{k}={v}' for k, v in self.axis_sizes.items())
        return f'<ShardingContext {ax} mode={self.mode}>'


class MeshGroup:
    """Mesh topology separated from process topology (the pod layer).

    A :class:`ShardingContext` describes a *device* mesh; a
    :class:`MeshGroup` describes which *host* (process) owns which
    slice of it — the ``jax.distributed`` view, emulated over
    ``n_procs`` local "hosts" on the CPU backend
    (``--xla_force_host_platform_device_count``) so pod-scale
    membership logic is tier-1 testable. Each host owns a contiguous
    block of ``len(devices) / n_procs`` devices; the group tracks the
    LIVE host set plus a re-formation ``generation``.

    The group is immutable: :meth:`eject` returns a NEW group with the
    dead hosts removed and the generation bumped — the shape handed to
    :meth:`context`, which builds a :class:`ShardingContext` over only
    the live hosts' devices (the re-formed, smaller mesh). The
    authoritative generation for stale-push rejection lives on the
    kvstore (``mesh_epoch`` verb); this one mirrors it for display and
    registration records.

    ``n_procs`` defaults to ``MXNET_MESH_PROCS`` (docs/env_vars.md).
    """

    def __init__(self, n_procs=None, devices=None, generation=0,
                 live=None):
        if n_procs is None:
            try:
                n_procs = int(os.environ.get('MXNET_MESH_PROCS', '1'))
            except ValueError:
                n_procs = 1
        n_procs = int(n_procs)
        devices = list(devices) if devices is not None \
            else list(jax.devices())
        if n_procs < 1:
            raise ValueError(f'n_procs must be >= 1, got {n_procs}')
        if len(devices) % n_procs:
            raise ValueError(
                f'{len(devices)} devices do not split evenly over '
                f'{n_procs} emulated hosts')
        self.n_procs = n_procs
        self._devices = devices
        per = len(devices) // n_procs
        self.devices_per_proc = per
        self._owned = {r: tuple(devices[r * per:(r + 1) * per])
                       for r in range(n_procs)}
        self.generation = int(generation)
        live = sorted(set(range(n_procs)) if live is None else
                      {int(r) for r in live})
        for r in live:
            if not 0 <= r < n_procs:
                raise ValueError(f'live rank {r} outside 0..{n_procs - 1}')
        if not live:
            raise ValueError('a MeshGroup needs at least one live host')
        self._live = tuple(live)

    # ---------------------------------------------------------- topology
    @property
    def live(self):
        """Live host ranks, ascending."""
        return self._live

    @property
    def leader(self):
        """Lowest live rank — the host that executes the global program
        and drives re-formation (leadership migrates on its death)."""
        return self._live[0]

    def devices_for(self, rank):
        """The contiguous device block host ``rank`` owns (dead or
        alive — ownership is topology, liveness is membership)."""
        return self._owned[int(rank)]

    def live_devices(self):
        """Union of the live hosts' devices, rank order — the device
        set the re-formed mesh is built over."""
        return [d for r in self._live for d in self._owned[r]]

    # -------------------------------------------------------- membership
    def eject(self, *ranks):
        """New group without ``ranks``, generation bumped — host loss
        (or planned scale-down) as a value, never in-place mutation."""
        gone = {int(r) for r in ranks}
        live = [r for r in self._live if r not in gone]
        if not live:
            raise ValueError(
                f'ejecting {sorted(gone)} would leave no live host')
        return MeshGroup(self.n_procs, self._devices,
                         generation=self.generation + 1, live=live)

    # ----------------------------------------------------------- context
    def context(self, tp=None, rules=None, mode=None, arch=None):
        """A :class:`ShardingContext` over the LIVE hosts' devices:
        ``dp`` = live devices / ``tp`` (default tp=1 — pure FSDP).
        Enter it with :func:`use`; deliberately not a contextmanager so
        drivers and servers can hold and re-enter one formation."""
        devs = self.live_devices()
        tp = int(tp) if tp else 1
        if tp > 1 and len(devs) % tp:
            raise ValueError(
                f'tp={tp} does not divide {len(devs)} live devices')
        dp = len(devs) // tp
        sizes = {}
        if dp > 1:
            sizes['dp'] = dp
        if tp > 1:
            sizes['tp'] = tp
        if not sizes:
            sizes = {'dp': len(devs)}
        from ..parallel.mesh import make_mesh
        return ShardingContext(make_mesh(devices=devs, **sizes),
                               rules=rules, mode=mode, arch=arch)

    def describe(self):
        """Registration-record form (serving: the router stores this
        per replica; training: the mesh_join meta)."""
        return {'n_procs': self.n_procs,
                'devices_per_proc': self.devices_per_proc,
                'n_devices': len(self._devices),
                'live': list(self._live),
                'generation': self.generation}

    def __repr__(self):
        return (f'<MeshGroup {len(self._live)}/{self.n_procs} hosts x '
                f'{self.devices_per_proc} dev gen={self.generation}>')


def constrain(x, spec=None):
    """``with_sharding_constraint`` under the active mesh; identity when
    no context is active (so library/model code may call it
    unconditionally). ``x`` may be an NDArray or a raw array; ``spec``
    defaults to the context's batch spec for the value's shape."""
    ctx = current()
    if ctx is None:
        return x
    from ..ndarray.ndarray import NDArray
    raw = x._data if isinstance(x, NDArray) else x
    if spec is None:
        spec = ctx.batch_spec(raw.shape)
    else:
        spec = _rules.resolve_spec(spec, raw.shape, ctx.mesh)
    out = jax.lax.with_sharding_constraint(
        raw, NamedSharding(ctx.mesh, spec))
    return NDArray(out) if isinstance(x, NDArray) else out


def batch_spec(shape):
    """The active context's batch spec for ``shape`` (P() when none)."""
    ctx = current()
    return ctx.batch_spec(tuple(shape)) if ctx is not None else P()


def lift_raws(raws):
    """Eager-op device reconciliation (called by ``ops.registry``).

    Inside a mesh context one dispatch may see arrays committed to the
    full mesh (sharded graph outputs) next to host-fresh single-device
    arrays (labels, loss masks) — jax rejects mixed committed device
    sets. Lift the single-device ones onto the mesh at their batch spec
    so eager loss/metric math composes with sharded forwards with zero
    model-code changes. No-op (same list back) when nothing is
    multi-device."""
    ctx = current()
    if ctx is None:
        return raws
    for r in raws:
        sh = getattr(r, 'sharding', None)
        if sh is not None and len(sh.device_set) > 1:
            break
    else:
        return raws
    out = []
    for r in raws:
        sh = getattr(r, 'sharding', None)
        if sh is not None and len(sh.device_set) == 1 \
                and getattr(r, 'ndim', None) is not None:
            r = jax.device_put(r, NamedSharding(
                ctx.mesh, ctx.batch_spec(r.shape)))
        out.append(r)
    return out


def _env_axis(name, value):
    env = os.environ.get(name, '')
    if env:
        return int(env)
    return value


@contextmanager
def mesh(dp=None, tp=None, devices=None, rules=None, mode=None,
         arch=None, **axes):
    """Scoped sharding over a device mesh built from axis sizes::

        with mx.sharding.mesh(dp=4, tp=2):
            net.hybridize()
            out = net(x)            # pjit-sharded, zero model changes

    ``dp``/``tp`` (and any extra named axes) size the mesh;
    ``MXNET_SHARDING_DP``/``MXNET_SHARDING_TP`` override them from the
    environment, and ``MXNET_SHARDING_DISABLE=1`` turns the whole
    context into a no-op. ``rules`` pins an explicit rule table;
    otherwise the registry table for ``arch`` (inferred per block when
    omitted) and the mode ('tp' when tp>1 else 'fsdp') applies.
    """
    if os.environ.get('MXNET_SHARDING_DISABLE', '') == '1':
        yield None
        return
    from ..parallel.mesh import make_mesh
    dp = _env_axis('MXNET_SHARDING_DP', dp)
    tp = _env_axis('MXNET_SHARDING_TP', tp)
    sizes = {}
    if dp and dp > 1:
        sizes['dp'] = dp
    if tp and tp > 1:
        sizes['tp'] = tp
    for k, v in axes.items():
        if v and v > 1:
            sizes[k] = v
    if not sizes:
        sizes = {'dp': len(devices or jax.devices())}
    ctx = ShardingContext(make_mesh(devices=devices, **sizes),
                          rules=rules, mode=mode, arch=arch)
    _stack().append(ctx)
    try:
        yield ctx
    finally:
        _stack().pop()


@contextmanager
def use(ctx):
    """Re-enter an existing :class:`ShardingContext` (e.g. one captured
    by a server at construction)."""
    if ctx is None:
        yield None
        return
    _stack().append(ctx)
    try:
        yield ctx
    finally:
        _stack().pop()
