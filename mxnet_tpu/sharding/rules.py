"""Partition-rule registry: regex -> PartitionSpec over the param pytree.

The single matcher behind every sharded surface in the repo
(``mx.sharding.mesh`` + the hybridize cache, ``parallel.shard_params``,
the sharded serve pool, the Trainer's ZeRO-1 slot placement). The
pattern is the one the SNIPPETS.md exemplars prove out at scale
(``match_partition_rules``): a rule table is an ordered list of
``(pattern, PartitionSpec)`` pairs, a parameter's *structural name*
(``collect_params()`` keys, e.g. ``model.layers0.self_attn.q_proj.weight``)
is matched with ``re.search`` against each pattern in order, and the
first match wins. Scalars (0-d params) auto-replicate without consulting
the table. A parameter no rule covers is an *error* naming the
nearest-missing rule — a silently replicated 7B embedding is exactly the
OOM the registry exists to prevent. (``parallel.shard_params`` keeps its
historical replicate-by-default behavior by passing
``on_unmatched='replicate'``.)

Rules also accept legacy *predicate* patterns — ``pred(name, shape) ->
bool`` callables — so the pre-registry rule sets
(``llama_partition_rules``) run through the same matcher unchanged.

Per-architecture tables ship for ``resnet``, ``bert`` and ``llama`` in
two modes:

* ``tp`` — Megatron tensor parallelism: column-parallel kernels shard
  the output dim on the ``tp`` mesh axis, row-parallel kernels the
  input dim, embeddings the vocab dim; norms/biases replicate.
* ``fsdp`` — ZeRO-3-style fully-sharded data parallel: every weight
  shards its leading dim on the ``dp`` mesh axis; small 1-d params
  replicate (sharding a (64,) gamma buys nothing and costs a gather).

``register_rules('myarch', 'tp', [...])`` adds user tables;
``rules_for(arch, mode)`` reads them back. ``resolve_spec`` adapts a
matched spec to a concrete (shape, mesh): any spec axis that does not
evenly divide its dim is dropped (that dim replicates) unless
``MXNET_SHARDING_STRICT=1``, which errors instead — documented in
docs/sharding.md.
"""

import difflib
import os
import re

from jax.sharding import PartitionSpec as P

__all__ = ['match_partition_rules', 'match_spec', 'resolve_spec',
           'register_rules', 'rules_for', 'list_archs', 'infer_arch',
           'UnmatchedParamError']


class UnmatchedParamError(ValueError):
    """A parameter matched no rule in the table (and the caller asked
    for errors, the registry default)."""


# --------------------------------------------------------------- the matcher
def _matches(pattern, name, shape):
    if isinstance(pattern, re.Pattern):
        return pattern.search(name) is not None
    if callable(pattern):
        return bool(pattern(name, shape))
    return re.search(pattern, name) is not None


def _pattern_label(pattern):
    if isinstance(pattern, re.Pattern):
        return pattern.pattern
    if callable(pattern):
        return getattr(pattern, '__name__', repr(pattern))
    return str(pattern)


def _shape_of(value):
    shape = getattr(value, 'shape', None)
    if shape is None and isinstance(value, (tuple, list)) and all(
            isinstance(d, int) for d in value):
        shape = tuple(value)
    if shape is None:
        raise TypeError(f'cannot read a shape from {type(value).__name__}')
    return tuple(shape)


def match_spec(name, shape_or_value, rules, on_unmatched='error'):
    """PartitionSpec for one parameter: first matching rule wins;
    0-d scalars replicate unconditionally.

    ``on_unmatched``: ``'error'`` raises :class:`UnmatchedParamError`
    naming the nearest rule (the registry contract); ``'replicate'``
    returns ``P()`` (the legacy ``shard_params`` contract).
    """
    shape = _shape_of(shape_or_value)
    if len(shape) == 0:
        return P()
    for pattern, spec in rules or []:
        if _matches(pattern, name, shape):
            return spec
    if on_unmatched == 'replicate':
        return P()
    labels = [_pattern_label(p) for p, _ in rules or []]
    near = difflib.get_close_matches(name, labels, n=1, cutoff=0.0)
    hint = f"; nearest rule: '{near[0]}'" if near else ''
    raise UnmatchedParamError(
        f"no partition rule matches parameter '{name}' "
        f'(shape {shape}){hint}. Add a rule via '
        "mx.sharding.register_rules(...) or pass rules=[...] "
        "covering it (scalars auto-replicate; an explicit "
        "(r'.*', PartitionSpec()) tail replicates the rest).")


def match_partition_rules(rules, params, on_unmatched='error'):
    """Match a whole param mapping (name -> shaped value / shape tuple)
    to ``{name: PartitionSpec}`` through one pass of the matcher."""
    return {name: match_spec(name, value, rules, on_unmatched=on_unmatched)
            for name, value in params.items()}


def strict_enabled():
    return os.environ.get('MXNET_SHARDING_STRICT', '') == '1'


def resolve_spec(spec, shape, mesh, name='<param>', strict=None):
    """Adapt a matched spec to a concrete (shape, mesh): axes whose mesh
    extent does not evenly divide the dim are dropped (that dim
    replicates), and axes missing from the mesh are dropped too. Under
    ``MXNET_SHARDING_STRICT=1`` (or ``strict=True``) a non-dividing
    axis raises instead."""
    if strict is None:
        strict = strict_enabled()
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    out = []
    for d, entry in enumerate(tuple(spec) + (None,) * (len(shape)
                                                       - len(spec))):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        axes = tuple(a for a in axes if a in sizes and sizes[a] > 1)
        extent = 1
        for a in axes:
            extent *= sizes[a]
        if extent > 1 and shape[d] % extent:
            if strict:
                raise ValueError(
                    f'{name}: dim {d} of shape {tuple(shape)} is not '
                    f'divisible by mesh axes {axes} (extent {extent}) '
                    '— MXNET_SHARDING_STRICT=1 forbids the replicate '
                    'fallback')
            axes = ()
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def shard_factor(spec, shape, mesh):
    """Number of devices one shard of this buffer is divided across:
    the product of resolved mesh-axis extents — the divisor for the
    per-device byte accounting in ``mx.analysis.costs``."""
    resolved = resolve_spec(spec, shape, mesh, strict=False)
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    factor = 1
    for entry in resolved:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            factor *= sizes.get(a, 1)
    return factor


# ----------------------------------------------------------- per-arch tables
# gluon Dense stores weight as (units_out, units_in): the output dim is
# axis 0 (column-parallel -> P('tp', None)); conv weight is
# (O, I, kh, kw).
_ARCH_RULES = {
    'llama': {
        'tp': [
            (r'(q_proj|k_proj|v_proj|gate_proj|up_proj)\.weight$',
             P('tp', None)),
            (r'(o_proj|down_proj)\.weight$', P(None, 'tp')),
            (r'(embed_tokens|lm_head)\.weight$', P('tp', None)),
            (r'(layernorm|norm)\.weight$', P()),
            (r'\.bias$', P()),
        ],
        'fsdp': [
            (r'(layernorm|norm)\.weight$', P()),
            (r'\.bias$', P()),
            (r'\.weight$', P('dp')),
        ],
    },
    'resnet': {
        # TP for convnets: shard output channels; BN stats/scales and
        # biases are per-channel 1-d — replicate (a (64,) gather costs
        # more than it saves).
        'tp': [
            (r'(conv|downsample).*weight$', P('tp')),
            (r'(dense|fc|output).*weight$', P('tp', None)),
            (r'(batchnorm|bn|norm)', P()),
            (r'(gamma|beta|running_mean|running_var)$', P()),
            (r'\.bias$', P()),
        ],
        'fsdp': [
            (r'(batchnorm|bn|norm)', P()),
            (r'(gamma|beta|running_mean|running_var)$', P()),
            (r'\.bias$', P()),
            (r'weight$', P('dp')),
        ],
    },
    'bert': {
        'tp': [
            (r'attention.*(query|key|value).*weight$', P('tp', None)),
            (r'(intermediate|ffn_1|ffn1).*weight$', P('tp', None)),
            (r'attention.*(proj|output|out_proj).*weight$', P(None, 'tp')),
            (r'(ffn_2|ffn2|output).*weight$', P(None, 'tp')),
            (r'(word_embed|token_embed|embed|position_weight)',
             P('tp', None)),
            (r'(layer_norm|layernorm|norm)', P()),
            (r'(gamma|beta)$', P()),
            (r'\.bias$', P()),
        ],
        'fsdp': [
            (r'(layer_norm|layernorm|norm)', P()),
            (r'(gamma|beta)$', P()),
            (r'\.bias$', P()),
            (r'weight$', P('dp')),
        ],
    },
    # zero-config fallback for arbitrary blocks: FSDP-style leading-dim
    # sharding for tensors, replicate the 1-d odds and ends. TP has no
    # generic answer — an unknown arch under mode='tp' must bring rules.
    'generic': {
        'fsdp': [
            (lambda name, shape: len(shape) <= 1, P()),
            (r'.*', P('dp')),
        ],
    },
}


def register_rules(arch, mode, rules):
    """Register (or replace) a rule table: ``register_rules('mymodel',
    'tp', [(r'attn.*weight', P('tp', None)), ...])``. Patterns are
    regexes (or ``pred(name, shape)`` callables); first match wins."""
    _ARCH_RULES.setdefault(arch, {})[mode] = list(rules)


def rules_for(arch, mode='tp'):
    """The registered rule table for (arch, mode). Raises KeyError with
    the available tables listed when there is none."""
    tables = _ARCH_RULES.get(arch)
    if tables is None or mode not in tables:
        have = sorted(f'{a}:{m}' for a, ms in _ARCH_RULES.items()
                      for m in ms)
        raise KeyError(
            f'no partition rules registered for arch={arch!r} '
            f'mode={mode!r}; have {have}. Register a table with '
            'mx.sharding.register_rules(arch, mode, rules).')
    return list(tables[mode])


def list_archs():
    return {a: sorted(ms) for a, ms in _ARCH_RULES.items()}


_ARCH_HINTS = (
    ('llama', 'llama'), ('bert', 'bert'), ('resnet', 'resnet'),
)


def infer_arch(block):
    """Best-effort architecture tag for a block (class-name match down
    the child tree); ``'generic'`` when nothing matches."""
    seen, stack = set(), [block]
    while stack:
        b = stack.pop()
        if id(b) in seen:
            continue
        seen.add(id(b))
        cls = type(b).__name__.lower()
        for hint, arch in _ARCH_HINTS:
            if hint in cls:
                return arch
        stack.extend(getattr(b, '_children', {}).values())
    return 'generic'
