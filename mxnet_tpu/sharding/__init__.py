"""``mx.sharding`` — zero-model-change SPMD sharding for training and
serving (ROADMAP item 1).

Two pieces, composed by ``gluon/block.py``'s hybridize cache:

* a **partition-rule registry** (:mod:`rules`): ordered
  ``(regex, PartitionSpec)`` tables over the structural param names,
  per-arch defaults for resnet/bert/llama in ``tp`` and ``fsdp`` modes,
  user-registrable via :func:`register_rules`. First match wins,
  scalars auto-replicate, an uncovered param errors naming the nearest
  rule.
* a **mesh-scoped context** (:mod:`context`): ``with mx.sharding.mesh(
  dp=4, tp=2):`` makes every hybridize compile inside it a pjit-sharded
  program — parameters placed per the rules, activations constrained at
  the graph boundary, donation preserved — keyed by the mesh
  fingerprint so mesh changes retrace (by design) and same-mesh reuse
  is warm.

Downstream consumers: ``gluon.Trainer`` partitions optimizer slots
along the data axis (ZeRO-1) inside the context; ``serve.DecodeServer``
shards the paged KV pool (pages on ``dp``, KV heads on ``tp``);
``mx.analysis`` lowers/audits the sharded program and reports
per-device costs. Everything runs on CPU under
``--xla_force_host_platform_device_count=8`` (tools/launch.py
``--cpu-mesh``), so tier-1 exercises real 8-device meshes.

See docs/sharding.md for rule syntax and TP/FSDP recipes, and
``parallel.init_distributed`` for the multi-host rendezvous.
"""

from .rules import (match_partition_rules, match_spec, resolve_spec,
                    shard_factor, register_rules, rules_for, list_archs,
                    infer_arch, UnmatchedParamError)
from .context import (ShardingContext, MeshGroup, mesh, current,
                      constrain, batch_spec, use, lift_raws)

# let the eager dispatch layer see the ambient mesh context (device-set
# reconciliation in apply_op) without a circular top-level import
from ..ops import registry as _registry
_registry._bind_sharding()
del _registry

__all__ = ['match_partition_rules', 'match_spec', 'resolve_spec',
           'shard_factor', 'register_rules', 'rules_for', 'list_archs',
           'infer_arch', 'UnmatchedParamError', 'ShardingContext',
           'MeshGroup', 'mesh', 'current', 'constrain', 'batch_spec',
           'use']
