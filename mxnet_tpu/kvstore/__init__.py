"""``mx.kvstore`` — distributed key-value parameter synchronization.

Reference: include/mxnet/kvstore.h:59 + src/kvstore/ (local/device comms,
NCCL, ps-lite dist_sync servers — SURVEY §2.1 KVStore row). TPU re-design
(SURVEY §2.3): the parameter-server stack is replaced wholesale by XLA
collectives. ``local``/``device`` aggregate across in-process device copies;
``dist_tpu_sync`` allreduces across hosts over ICI/DCN via
``jax.distributed`` + psum — no server processes, no ZMQ, no NCCL. The
KVStore *API* (init/push/pull/pushpull/broadcast/rank/num_workers/barrier +
the optimizer/updater hooks) is preserved so Trainer and reference example
code run unchanged.
"""

from .base import KVStoreBase
from .kvstore import KVStore, KVStoreLocal
from .tpu import KVStoreTPUSync
from .plugins import Horovod, BytePS
from .dist_async import KVStoreDistAsync


def create(name='local'):
    """Factory (reference src/kvstore/kvstore.cc:42 KVStore::Create +
    python/mxnet/kvstore/kvstore.py create)."""
    if not isinstance(name, str):
        raise TypeError('name must be a string')
    return KVStoreBase.get_kvstore(name)
