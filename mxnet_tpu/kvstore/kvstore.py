"""In-process KVStore types: ``local`` and ``device``.

Reference: src/kvstore/kvstore_local.h:70 + comm.h (CommCPU :104 /
CommDevice :452 — the GPU reduce trees). On TPU a single process owns all
local chips; "reduce across device copies" is one stacked jnp.sum that XLA
executes with on-chip ICI transfers, so CommDevice/CommDeviceTree collapse
into one fused reduction. The updater/optimizer hooks
(set_updater/set_optimizer, include/mxnet/kvstore.h:297) are preserved.
"""

import jax.numpy as jnp

from ..ndarray.ndarray import NDArray
from .base import KVStoreBase, register


def _group(keys, values):
    """Group possibly-flat (key, value) lists by key
    (reference kvstore_local.h GroupKVPairs)."""
    if not isinstance(keys, (list, tuple)):
        return [(keys, values if isinstance(values, (list, tuple))
                 else [values])]
    if len(keys) == len(values) and not any(
            isinstance(v, (list, tuple)) for v in values):
        merged = {}
        order = []
        for k, v in zip(keys, values):
            if k not in merged:
                merged[k] = []
                order.append(k)
            merged[k].append(v)
        return [(k, merged[k]) for k in order]
    return [(k, v if isinstance(v, (list, tuple)) else [v])
            for k, v in zip(keys, values)]


def _reduce(values):
    """Sum a list of NDArray replicas (CommDevice::Reduce, comm.h:452)."""
    if len(values) == 1:
        return values[0]._data
    return jnp.sum(jnp.stack([v._data for v in values]), axis=0)


@register
class KVStoreLocal(KVStoreBase):
    """Reference kvstore_local.h:70 — single-process aggregation."""

    NAME = 'local'

    def __init__(self):
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._states = {}

    # ------------------------------------------------------- classic surface
    def init(self, key, value):
        from ..ndarray import sparse as _sp
        for k, vals in _group(key, value):
            v = vals[0]
            if isinstance(v, _sp.BaseSparseNDArray):
                self._store[k] = v.copy()   # keep sparse storage
            else:
                self._store[k] = NDArray(v._data, ctx=v._ctx)

    def push(self, key, value, priority=0):
        for k, vals in _group(key, value):
            merged = _reduce(vals)
            if self._updater is not None and k in self._store:
                self._updater(k, NDArray(merged), self._store[k])
            elif k in self._store:
                self._store[k]._rebind(self._store[k]._data + merged)
            else:
                self._store[k] = NDArray(merged)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        for k, outs in _group(key, out):
            src = self._store[k]
            for o in outs:
                o._rebind(src._data)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference PushPullDefault kvstore_dist.h:578).

        Without an updater this is a pure allreduce: out ← sum(value).
        """
        for k, vals in _group(key, value):
            merged = _reduce(vals)
            if self._updater is not None:
                if k not in self._store:
                    raise ValueError(
                        f'pushpull with an updater requires key {k!r} to be '
                        'initialized first (init/broadcast), matching the '
                        'reference KVStore contract')
                self._updater(k, NDArray(merged), self._store[k])
                result = self._store[k]._data
            else:
                result = merged
            if out is not None:
                outs = [o for kk, os in _group(key, out) if kk == k
                        for o in os]
                for o in outs:
                    o._rebind(result)
            else:
                for v in vals:
                    v._rebind(result)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out=out, priority=priority)

    # ---------------------------------------------------------- fused path
    def fused_pushpull(self, keys, values, outs=None, priorities=None):
        """Multi-key pushpull in as few device programs as possible.

        ``values[i]`` is the replica list for ``keys[i]``. All keys'
        replica reductions run in ONE jitted executable (the role the
        reference's per-key ``CommDevice::Reduce`` + engine bulking
        played); the distributed subclass adds bucketed cross-process
        collectives on top. ``priorities`` is accepted for API parity;
        ordering only matters in the distributed subclass, where it
        sequences bucket dispatch (reference Trainer's ``priority=-i``).
        """
        vals_lists = [v if isinstance(v, (list, tuple)) else [v]
                      for v in values]
        merged = self._merge_local(keys, vals_lists)
        self._apply_merged(keys, merged, vals_lists, outs)

    def _merge_local(self, keys, vals_lists):
        from . import fusion
        raws = [[v._data for v in vs] for vs in vals_lists]
        if any(len(r) > 1 for r in raws):
            return fusion._fused_replica_sum(raws)
        return [r[0] for r in raws]

    def _apply_merged(self, keys, merged, vals_lists, outs):
        for i, k in enumerate(keys):
            if self._updater is not None:
                if k not in self._store:
                    raise ValueError(
                        f'pushpull with an updater requires key {k!r} to '
                        'be initialized first (init/broadcast)')
                self._updater(k, NDArray(merged[i]), self._store[k])
                result = self._store[k]._data
            else:
                result = merged[i]
            targets = outs[i] if outs is not None else vals_lists[i]
            if not isinstance(targets, (list, tuple)):
                targets = [targets]
            for t in targets:
                t._rebind(result)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (reference kvstore.py
        row_sparse_pull → PullRowSparse, include/mxnet/kvstore.h:221).

        With a RowSparseNDArray stored value, returns/updates the retained
        rows; dense stored values gather the requested rows into the dense
        output (the useful TPU form: gather over a sharded embedding axis,
        SURVEY §5 last row)."""
        from ..ndarray import sparse as _sp
        if isinstance(key, (list, tuple)):
            rids = row_ids if isinstance(row_ids, (list, tuple)) else \
                [row_ids] * len(key)
            outs = out if isinstance(out, (list, tuple)) else \
                [None] * len(key)
            return [self.row_sparse_pull(k, out=o, priority=priority,
                                         row_ids=r)
                    for k, o, r in zip(key, outs, rids)]
        value = self._store[key]
        if row_ids is None:
            self.pull(key, out=out, priority=priority)
            return out
        if isinstance(value, _sp.RowSparseNDArray):
            res = _sp.retain(value, row_ids)
            if out is not None:
                outs = out if isinstance(out, (list, tuple)) else [out]
                for o in outs:
                    o.data = res.data
                    o.indices = res.indices
                    o._invalidate()
                return out
            return res
        import jax.numpy as jnp
        rows = row_ids._data.astype(jnp.int32) if hasattr(row_ids, '_data') \
            else jnp.asarray(row_ids, jnp.int32)
        gathered = value._data.at[rows].get()
        if out is not None:
            outs = out if isinstance(out, (list, tuple)) else [out]
            for o in outs:
                if isinstance(o, _sp.RowSparseNDArray):
                    # actual row slices — never densify the pull
                    o.data = NDArray(gathered)
                    o.indices = NDArray(rows.astype(jnp.int64))
                    o._invalidate()
                else:
                    o._rebind(o._data.at[rows].set(gathered))
            return out
        # no out given: return the row slices themselves (O(nnz), not
        # O(table) — a 10M-row embedding pull must not densify)
        return _sp.RowSparseNDArray(NDArray(gathered),
                                    NDArray(rows.astype(jnp.int64)),
                                    value.shape)

    # ------------------------------------------------------ optimizer hooks
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        from ..optimizer import get_updater
        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """Enable 2-bit compression (reference SetGradientCompression,
        include/mxnet/kvstore.h + gradient_compression.h:37). On the
        local store this only validates/records params — like the
        reference, compression is applied on the distributed hop
        (KVStoreTPUSync), not on in-process reduction."""
        from .gradient_compression import GradientCompression
        gc = GradientCompression()
        gc.set_params(compression_params)
        if gc.active and type(self) in (KVStoreLocal, KVStoreDevice):
            # the reference raises for kvstore types without compression
            # support (kvstore.cc); we accept for API parity but make
            # the no-op visible
            import warnings
            warnings.warn(
                f'gradient compression is a no-op on the {self.NAME!r} '
                'kvstore: it applies only on the distributed hop '
                '(dist_tpu_sync)', UserWarning, stacklevel=2)
        self._gc = gc

    @property
    def gradient_compression(self):
        gc = getattr(self, '_gc', None)
        if gc is None:
            from .gradient_compression import GradientCompression
            gc = self._gc = GradientCompression()
        return gc

    # ------------------------------------------------------------- topology
    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def barrier(self):
        pass

    @property
    def type(self):
        return self.NAME

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, 'updater is not initialized'
        with open(fname, 'wb') as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, 'updater is not initialized'
        with open(fname, 'rb') as f:
            self._updater.set_states(f.read())

    @staticmethod
    def is_capable(capability):
        return capability.lower() in ('optimizer', 'init')


@register
class KVStoreDevice(KVStoreLocal):
    """Reference 'device' type: aggregation on-accelerator (CommDevice).
    Identical here — the reduce already runs on TPU."""

    NAME = 'device'


KVStore = KVStoreLocal  # classic class name (python/mxnet/kvstore/kvstore.py)
