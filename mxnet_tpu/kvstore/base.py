"""KVStore plugin registry (reference python/mxnet/kvstore/base.py:249,432).

Backends register by name; ``create('horovod')`` etc. resolve here — the
same surface the reference exposes so external backends can plug in.
"""

KVSTORE_REGISTRY = {}


def register(klass):
    """Register a KVStoreBase subclass (reference kvstore/base.py:432)."""
    name = getattr(klass, 'NAME', klass.__name__).lower()
    KVSTORE_REGISTRY[name] = klass
    return klass


class KVStoreBase:
    """Abstract KVStore (reference kvstore/base.py:249).

    Methods mirror include/mxnet/kvstore.h: broadcast ≙ Init+Pull (:105,187),
    pushpull ≙ PushPull (:237), plus the classic push/pull split.
    """

    @staticmethod
    def register(klass):
        return register(klass)

    @staticmethod
    def get_kvstore(name):
        name = name.lower()
        # reference type-string aliases (src/kvstore/kvstore.cc:42-85)
        aliases = {
            'local_allreduce_cpu': 'local',
            'local_allreduce_device': 'device',
            'nccl': 'device',
            'dist': 'dist_tpu_sync',
            'dist_sync': 'dist_tpu_sync',
            'dist_sync_device': 'dist_tpu_sync',
            'dist_device_sync': 'dist_tpu_sync',
        }
        name = aliases.get(name, name)
        if name not in KVSTORE_REGISTRY:
            raise ValueError(
                f'Unknown KVStore type {name!r}; registered: '
                f'{sorted(KVSTORE_REGISTRY)}')
        return KVSTORE_REGISTRY[name]()

    def broadcast(self, key, value, out, priority=0):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    @staticmethod
    def is_capable(capability):
        raise NotImplementedError

    @property
    def rank(self):
        raise NotImplementedError

    @property
    def num_workers(self):
        raise NotImplementedError

    OPTIMIZER = 'optimizer'
