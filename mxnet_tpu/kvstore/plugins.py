"""Horovod / BytePS kvstore plugins — the reference's delegation
structure over an injectable backend.

Reference: ``python/mxnet/kvstore/horovod.py:25-160`` (broadcast →
``hvd.broadcast``, pushpull → ``hvd.allreduce``/``allreduce_``,
rank/local_rank/size from the hvd module) and
``python/mxnet/kvstore/byteps.py:26-224`` (byteps_declare_tensor +
byteps_push_pull; broadcast = zero-on-non-root then push_pull).

This zero-egress image cannot link the real horovod/byteps wheels, so
the backend is DUCK-TYPED: anything exposing the hvd (or bps) call
surface can be injected with ``Horovod.set_backend(module)`` /
``BytePS.set_backend(module)`` — tests drive the full delegation path
with a mock backed by a real XLA psum over the local device mesh. When
no backend is injected and the real package is not importable, both
classes keep their documented COMPAT-ALIAS behavior: the same
allreduce semantics the plugin would provide, executed as XLA
collectives by :class:`KVStoreTPUSync` (scripts written against the
plugin surface run unchanged).
"""

from .base import register
from .tpu import KVStoreTPUSync


def _reduce_replicas(vals):
    """Sum a list of local device replicas into one tensor (the base
    store's pre-allreduce local reduction) so a single collective
    carries the whole contribution."""
    if len(vals) == 1:
        return vals[0]
    acc = vals[0].copy()
    for v in vals[1:]:
        acc[:] = acc + v
    return acc


def _resolve_backend(injected, module_name):
    if injected is not None:
        return injected
    try:
        import importlib
        return importlib.import_module(module_name)
    except ImportError:
        return None


@register
class Horovod(KVStoreTPUSync):
    """COMPAT ALIAS + delegation shell for the Horovod plugin.

    With a backend (injected via :meth:`set_backend`, or a real
    ``horovod.mxnet`` if one is installed) every collective delegates
    exactly like the reference ``KVStoreHorovod``; without one the
    class is a documented COMPAT ALIAS executing the same allreduce
    topology over XLA collectives. No hvd transport exists in this
    zero-egress image, so CI exercises the delegation with a mock hvd
    whose allreduce is a real psum over the local mesh
    (tests/test_kvstore.py)."""

    NAME = 'horovod'
    _backend = None                  # class-level injection point

    @classmethod
    def set_backend(cls, hvd):
        """Inject an hvd-like module (``init/rank/local_rank/size/
        broadcast/allreduce/allreduce_``). ``None`` restores the
        XLA-collective alias behavior."""
        cls._backend = hvd

    def __init__(self):
        super().__init__()
        self._hvd = _resolve_backend(type(self)._backend, 'horovod.mxnet')
        if self._hvd is not None:
            self._hvd.init()         # reference horovod.py:30

    # ------------------------------------------------------- delegation
    def broadcast(self, key, value, out, priority=0):
        """Reference horovod.py:42: rank-0's value to every rank's out
        via ``hvd.broadcast``."""
        if self._hvd is None:
            return super().broadcast(key, value, out, priority)
        if isinstance(value, (list, tuple)):
            # first replica wins: broadcast ships a VALUE (rank 0's
            # weights), so k identical per-device replicas must not be
            # summed into k× the tensor — the replica sum belongs to
            # pushpull's gradient semantics only (ADVICE r5)
            value = value[0]
        outs = out if isinstance(out, (list, tuple)) else [out]
        res = self._hvd.broadcast(tensor=value, root_rank=0,
                                  name=str(key), priority=priority)
        for o in outs:
            o[:] = res

    def pushpull(self, key, value, out=None, priority=0):
        """Reference horovod.py:78: allreduce_ in place when no out,
        else allreduce into out (sum, never average). Replica lists
        (one value per local device, the base-store surface) are summed
        locally first so one allreduce carries the full contribution
        and EVERY out target receives the result."""
        if self._hvd is None:
            return super().pushpull(key, value, out, priority)
        if out is None:
            vals = value if isinstance(value, (list, tuple)) else [value]
            for v in vals:
                self._hvd.allreduce_(v, average=False, name=str(key),
                                     priority=priority)
        else:
            outs = out if isinstance(out, (list, tuple)) else [out]
            v = _reduce_replicas(value) \
                if isinstance(value, (list, tuple)) else value
            res = self._hvd.allreduce(v, average=False, name=str(key),
                                      priority=priority)
            for o in outs:
                o[:] = res

    def set_optimizer(self, optimizer):
        """Reference horovod.py:135: the plugin never runs the optimizer
        on a server — Trainer keeps updates local."""
        if self._hvd is None:
            return super().set_optimizer(optimizer)

    @property
    def rank(self):
        return self._hvd.rank() if self._hvd is not None else super().rank

    @property
    def local_rank(self):
        if self._hvd is not None:
            return self._hvd.local_rank()
        import jax
        return jax.process_index()

    @property
    def num_workers(self):
        return self._hvd.size() if self._hvd is not None \
            else super().num_workers

    @property
    def type(self):
        return 'horovod' if self._hvd is not None else super().type


@register
class BytePS(KVStoreTPUSync):
    """COMPAT ALIAS + delegation shell for the BytePS plugin (reference
    ``python/mxnet/kvstore/byteps.py:26``) — see Horovod note above.

    Delegation mirrors the reference call structure: every tensor is
    announced with ``byteps_declare_tensor`` and summed in place with
    ``byteps_push_pull``; broadcast zeroes the value on non-root ranks
    first, so the push_pull sum equals rank-0's value."""

    NAME = 'byteps'
    _backend = None

    @classmethod
    def set_backend(cls, bps):
        cls._backend = bps

    def __init__(self):
        super().__init__()
        self._bps = _resolve_backend(type(self)._backend, 'byteps.mxnet')
        if self._bps is not None:
            self._bps.init()         # reference byteps.py:43

    def _push_pull_inplace(self, key, tensor, priority):
        self._bps.byteps_declare_tensor(str(key))
        self._bps.byteps_push_pull(tensor, version=0, priority=priority,
                                   name=str(key), is_average=False)

    def broadcast(self, key, value, out, priority=0):
        """Reference byteps.py:46-102: non-root ranks zero their copy,
        then the push_pull sum carries rank-0's value to everyone."""
        if self._bps is None:
            return super().broadcast(key, value, out, priority)
        if isinstance(value, (list, tuple)):
            if len(value) != 1:
                # reference byteps.py asserts a single tensor; letting
                # a k-replica list through would push `list * 0 == []`
                # to the backend — garbage, not a broadcast (ADVICE r5)
                raise ValueError(
                    'byteps broadcast takes a single tensor per key, '
                    f'got a {len(value)}-element replica list for key '
                    f'{key!r} (reference byteps.py asserts '
                    'a single NDArray)')
            value = value[0]
        outs = out if isinstance(out, (list, tuple)) else [out]
        inplace = len(outs) == 1 and value is outs[0]
        bval = value if inplace else value.copy()
        if self.rank != 0:
            bval[:] = bval * 0       # reference: __imul__(0) on non-root
        self._push_pull_inplace(key, bval, priority)
        bval.wait_to_read()          # reference: sync before training
        for o in outs:
            if o is not bval:
                o[:] = bval

    def pushpull(self, key, value, out=None, priority=0):
        """Reference byteps.py:105-160: declare + push_pull, in place
        when no out, else through a scratch copy into out. Replica
        lists are summed locally first (the base store's pre-allreduce
        reduction) so no device's gradient is dropped."""
        if self._bps is None:
            return super().pushpull(key, value, out, priority)
        vals = value if isinstance(value, (list, tuple)) else [value]
        if out is None:
            for v in vals:
                self._push_pull_inplace(key, v, priority)
            return
        outs = out if isinstance(out, (list, tuple)) else [out]
        scratch = _reduce_replicas(vals)
        if scratch is vals[0]:
            scratch = vals[0].copy()
        self._push_pull_inplace(key, scratch, priority)
        for o in outs:
            o[:] = scratch

    @property
    def rank(self):
        return self._bps.rank() if self._bps is not None else super().rank

    @property
    def local_rank(self):
        if self._bps is not None:
            return self._bps.local_rank()
        import jax
        return jax.process_index()

    @property
    def num_workers(self):
        return self._bps.size() if self._bps is not None \
            else super().num_workers

    @property
    def type(self):
        return 'byteps' if self._bps is not None else super().type
