"""Generic framed-RPC transport shared by ``dist_async`` and ``serve``.

Extracted from ``kvstore/dist_async.py`` so the replicated serving tier
(``mxnet_tpu/serve/router.py`` / ``replica.py``) can speak the same
fault-tolerant wire protocol without duplicating the socket layer:

* :func:`_send_msg` / :func:`_recv_msg` — the JSON-header + raw-bytes
  framing (no pickle on the generic path: a reachable port cannot
  execute code via a crafted header), with the deterministic
  fault-injection hooks from :mod:`mxnet_tpu.kvstore.faults` inline.
* :class:`RpcServer` — a threaded TCP server owning the machinery every
  service needs and none of the semantics: per-connection handler loop,
  heartbeat ``_last_seen`` table with bye-tombstones, the ``(client,
  seq)`` exactly-once dedup window (``MXNET_KVSTORE_DEDUP_WINDOW``),
  and a ``crash()`` switch for chaos tests. Services subclass and
  implement :meth:`RpcServer._handle_app`; built-in commands ``ping`` /
  ``bye`` / ``dead_nodes`` are answered here (``ping`` merges
  :meth:`RpcServer._ping_extra`, which is how replicas piggyback load
  onto heartbeats).
* :class:`RpcClient` — one retrying channel to one server address:
  per-call deadline, exponential backoff + jitter, redial on any
  transport failure, shared ``retries``/``redials``/``giveups``
  counters. Identity stamping (rank, ``(client, seq)``) stays with the
  caller — the router must reuse one identity across failover attempts,
  so the channel never invents one.

Env knobs (same names as the kvstore transport — one set of semantics):
``MXNET_KVSTORE_RPC_RETRIES`` / ``MXNET_KVSTORE_RPC_DEADLINE_S`` /
``MXNET_KVSTORE_RPC_BACKOFF_S`` / ``MXNET_KVSTORE_DEDUP_WINDOW``.
"""

import collections
import json
import os
import socket
import socketserver
import struct
import threading
import time as _time

from . import faults
from ..telemetry import trace as _trace
from ..telemetry import metrics as _tmetrics

# fleet-wide transport counters (registry instruments, mergeable over
# the 'metrics' verb); each channel's per-instance stats dict remains
# the local thin view
_C_RETRIES = _tmetrics.counter('mx_rpc_retries_total')
_C_REDIALS = _tmetrics.counter('mx_rpc_redials_total')
_C_GIVEUPS = _tmetrics.counter('mx_rpc_giveups_total')
_C_REPLAYS = _tmetrics.counter('mx_rpc_dedup_replays_total')
# pod-scale mesh membership (docs/fault-tolerance.md "Pod-scale
# elasticity"): generation gauge follows every join/leave/epoch bump,
# the reject counter every fenced-off stale-generation request
_G_MESH_GEN = _tmetrics.gauge('mx_mesh_generation')
_C_STALE_GEN = _tmetrics.counter('mx_mesh_stale_generation_rejects_total')


class StaleGeneration(RuntimeError):
    """A generation-stamped request (push/pull/put of a mesh member)
    carried a mesh generation older than the server's: the sender
    missed a re-formation — typically a host that was ejected but is
    still running. The request is REJECTED with this typed error, never
    silently applied: a zombie's gradients must not leak into a mesh
    that already rolled back past them. The client refreshes its
    generation via ``mesh_epoch``/``mesh_table`` and rejoins."""


def _recv_exact(sock, n):
    buf = b''
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError('kvstore async peer closed')
        buf += chunk
    return buf


def _send_msg(sock, header, payload=b''):
    faults.on_send(header)          # no-op unless a fault plan is armed
    head = json.dumps(header).encode('utf-8')
    sock.sendall(struct.pack('!II', len(head), len(payload)))
    sock.sendall(head)
    if payload:
        sock.sendall(payload)


def _recv_msg(sock):
    faults.on_recv(sock)            # no-op unless a fault plan is armed
    hlen, plen = struct.unpack('!II', _recv_exact(sock, 8))
    header = json.loads(_recv_exact(sock, hlen).decode('utf-8'))
    payload = _recv_exact(sock, plen) if plen else b''
    return header, payload


class RpcServer(threading.Thread):
    """Threaded TCP server speaking the framed protocol.

    Owns the transport-level state machine; application semantics live
    in subclasses via :meth:`_handle_app`. Request flow per message::

        _recv_msg -> _dispatch (heartbeat refresh, dedup window)
                  -> _handle (ping/bye/dead_nodes) -> _handle_app
                  -> _pre_reply hook -> _send_msg

    Any exception out of the handler becomes an ``ok: False`` reply and
    the connection stays alive; transport errors drop the connection
    (the peer's retrying client redials and the dedup window makes the
    resend exactly-once).
    """

    #: race-checker level for ``self._lock`` (subclasses override)
    LOCK_LEVEL = 'kvstore.store'
    # data-plane commands prove a live store: they lift a tombstone (a
    # NEW incarnation of a departed rank revives it); ping/bye/queries
    # do not (the ADVICE r5 heartbeat race)
    _REVIVING_CMDS = frozenset()

    def __init__(self, port, bind_host='127.0.0.1', sid=0):
        super().__init__(daemon=True)
        self._sid = sid
        self._lock = threading.Lock()
        # injectable clock: every liveness decision (heartbeat stamps,
        # dead_nodes cutoff, elastic ejection) reads THIS, so chaos
        # tests advance a fake clock deterministically instead of
        # sleeping past real deadlines
        self._clock = _time.monotonic
        self._last_seen = {}        # peer rank -> monotonic last beat
        self._tombstones = set()    # ranks that sent 'bye'
        # (client, seq) -> (reply, rpayload) replay window for retried
        # mutating RPCs whose reply was lost after the server applied
        # them: exactly-once under retry (≙ ps-lite resender dedup)
        self._dedup = {}
        self._dedup_order = collections.deque()
        self._dedup_window = int(os.environ.get(
            'MXNET_KVSTORE_DEDUP_WINDOW', '512'))
        self._counters = {'dedup_replays': 0, 'stale_gen_rejects': 0}
        # mesh membership table (mesh_join/mesh_leave/mesh_epoch): the
        # process-topology side of a MeshGroup. Guarded by self._lock
        # (kvstore.store) — no new lock level. The generation bumps on
        # every membership change; generation-stamped data-plane
        # requests older than it are rejected with StaleGeneration.
        self._mesh_members = {}     # rank -> {'joined': clock, 'meta': {}}
        self._mesh_gen = 0
        # live handler sockets: crash() force-closes them so an
        # injected replica death severs in-flight requests the way a
        # real process kill would (socketserver itself never tracks
        # accepted connections)
        self._conns = set()
        self._conns_lock = threading.Lock()
        from ..analysis import race as _race
        if _race.enabled():
            self._lock = _race.tracked(self._lock, self.LOCK_LEVEL)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    self._serve_loop()
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

            def _serve_loop(self):
                while True:
                    try:
                        header, payload = _recv_msg(self.request)
                    except (ConnectionError, OSError, ValueError):
                        return
                    try:
                        reply, rpayload = outer._dispatch(
                            header, payload, self.client_address[0])
                    except ConnectionError:
                        # injected crash/partition (serve.faults raises
                        # ConnectionError subclasses): sever with no
                        # reply — the peer sees a dead endpoint, not an
                        # application error
                        return
                    except Exception as e:    # keep the connection alive
                        reply, rpayload = {'ok': False,
                                           'error': repr(e)}, b''
                    try:
                        # chaos hook: an injected reply-loss fault makes
                        # this raise AFTER the handler applied — the
                        # retry then exercises the dedup window
                        outer._pre_reply(header)
                    except Exception:
                        return            # reply lost: drop the socket
                    try:
                        _send_msg(self.request, reply, rpayload)
                    except (ConnectionError, OSError):
                        # the peer reset/closed mid-reply (e.g. its
                        # retrying RPC layer already gave up on this
                        # socket): it will resend on a fresh connection
                        # and the dedup window answers
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        # bind the advertised interface (not 0.0.0.0): peers reach us
        # at this address anyway, and nothing else should
        try:
            self._server = Server((bind_host, port), Handler)
        except OSError:
            # the hostname may not be a local interface name
            # (NAT/containers): fall back to all interfaces like ps-lite
            self._server = Server(('0.0.0.0', port), Handler)

    @property
    def port(self):
        """The actually-bound port (useful with ``port=0`` ephemerals)."""
        return self._server.server_address[1]

    def set_clock(self, fn):
        """Swap the liveness clock (tests: a fake monotonic source).
        Returns the previous clock."""
        prev, self._clock = self._clock, fn
        return prev

    def run(self):
        self._server.serve_forever(poll_interval=0.05)

    def stop(self):
        if self.is_alive():
            self._server.shutdown()
        self._server.server_close()

    def release_port(self):
        """Drop the post-crash port hold so a successor may bind the
        advertised port (no-op unless :meth:`crash` ran)."""
        hold = getattr(self, '_port_hold', None)
        if hold is not None:
            self._port_hold = None
            try:
                hold.close()
            except OSError:
                pass

    def crash(self):
        """Abrupt death for chaos tests: stop accepting, force-close
        every live connection mid-flight — no replies, no farewells —
        exactly what a killed replica process looks like to its peers.
        The instance is dead afterwards; recovery is a NEW server on
        the same port (see ``serve.replica.Replica.restart``)."""
        addr = self._server.server_address
        if self.is_alive():
            self._server.shutdown()
        self._server.server_close()
        # Hold the freed port with a bound, non-listening socket:
        # peers still get connection-refused (dead-process semantics),
        # but the OS cannot hand the port out as an ephemeral source
        # port to some unrelated connection, which would make the
        # same-port restart fail EADDRINUSE. release_port() drops it.
        try:
            hold = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            hold.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            hold.bind(addr)
            self._port_hold = hold
        except OSError:
            pass                        # already stolen; restart retries
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    # -------------------------------------------------------------- hooks
    def _ping_extra(self):
        """Extra fields merged into every ``ping`` reply — replicas
        piggyback their load snapshot here so heartbeats double as the
        router's least-loaded routing feed. Must not block."""
        return None

    def _pre_reply(self, header):
        """Called after the handler ran, before the reply is sent; a
        raise here LOSES the reply (connection dropped) while the
        apply stands — the chaos hook for dedup-window tests."""

    # ----------------------------------------------------------- dispatch
    def _dispatch(self, header, payload, peer='127.0.0.1'):
        """Trace adoption around :meth:`_dispatch_inner`: when the
        envelope carries a ``tc`` context (injected by a tracing
        :class:`RpcClient`; old peers simply never send one) the whole
        server-side handling becomes a ``rpc.handle:<cmd>`` span in the
        caller's trace — including an injected crash, which lands as
        the span's ``error`` attr before the connection severs."""
        tc = header.get('tc')
        if not tc or not _trace.enabled():
            return self._dispatch_inner(header, payload, peer)
        with _trace.attach(tc):
            with _trace.span('rpc.handle:%s' % header['cmd'],
                             sid=self._sid):
                return self._dispatch_inner(header, payload, peer)

    def _dispatch_inner(self, header, payload, peer):
        """Bookkeeping envelope around :meth:`_handle`: heartbeat
        refresh (tombstone-gated), then the (client, seq) dedup window
        — a retried mutating RPC the server already applied gets its
        cached reply replayed instead of a second apply."""
        cmd = header['cmd']
        rank = header.get('rank')
        client, seq = header.get('client'), header.get('seq')
        gen = header.get('gen')
        with self._lock:
            if rank is not None:
                r = int(rank)
                if r not in self._tombstones:
                    # every RPC doubles as a heartbeat (plus any
                    # dedicated ping thread on the peer)
                    self._last_seen[r] = self._clock()
                elif cmd in self._REVIVING_CMDS:
                    self._tombstones.discard(r)
                    self._last_seen[r] = self._clock()
            if gen is not None and int(gen) < self._mesh_gen:
                # generation fence — checked BEFORE the dedup window so
                # a stale sender always gets the typed rejection, even
                # for a retry whose pre-reformation apply was cached
                # (the mesh rolled back past it either way)
                self._counters['stale_gen_rejects'] += 1
                _C_STALE_GEN.inc()
                return ({'ok': False, 'kind': 'StaleGeneration',
                         'error': f'{cmd!r} rejected: stale mesh '
                                  f'generation {int(gen)} < '
                                  f'{self._mesh_gen} — the mesh '
                                  're-formed; refresh via mesh_epoch '
                                  'and rejoin',
                         'mesh_gen': self._mesh_gen}, b'')
            if client is not None and seq is not None:
                cached = self._dedup.get((client, int(seq)))
                if cached is not None:
                    self._counters['dedup_replays'] += 1
                    _C_REPLAYS.inc()
                    return cached
        reply, rpayload = self._handle(header, payload, peer)
        if client is not None and seq is not None and reply.get('ok'):
            # only successful applies enter the window: a failed
            # attempt must re-execute, not replay its error
            with self._lock:
                key = (client, int(seq))
                if key not in self._dedup:
                    self._dedup[key] = (reply, rpayload)
                    self._dedup_order.append(key)
                    while len(self._dedup_order) > self._dedup_window:
                        self._dedup.pop(self._dedup_order.popleft(),
                                        None)
        return reply, rpayload

    def _handle(self, header, payload, peer='127.0.0.1'):
        cmd = header['cmd']
        if cmd == 'ping':
            # ts/proc: the peer's wall clock + process identity, read by
            # telemetry.note_clock on the caller for cross-process trace
            # alignment (NTP-midpoint offset off this one round trip)
            reply = {'ok': True, 'sid': self._sid,
                     'ts': _time.time(), 'proc': _trace.proc_name()}
            with self._lock:
                if self._mesh_members or self._mesh_gen:
                    # membership table piggybacked on every heartbeat:
                    # followers learn re-formations (new generation,
                    # shrunk member set) without a dedicated poll verb
                    reply['mesh'] = {'gen': self._mesh_gen,
                                     'members': sorted(self._mesh_members)}
            extra = self._ping_extra()
            if extra:
                reply.update(extra)
            return reply, b''
        if cmd == 'bye':
            # clean departure: drop the rank from the last-seen table
            # so dead_nodes does not report a finished peer as dead
            # forever (ADVICE r4), and tombstone it so a delayed
            # in-flight ping cannot re-add it afterwards (ADVICE r5)
            with self._lock:
                self._last_seen.pop(int(header['rank']), None)
                self._tombstones.add(int(header['rank']))
            return {'ok': True}, b''
        if cmd == 'dead_nodes':
            cutoff = self._clock() - float(header['timeout'])
            with self._lock:
                dead = sum(1 for t in self._last_seen.values()
                           if t < cutoff)
                departed = len(self._tombstones)
            # tombstoned ranks left CLEANLY: reported separately, never
            # counted dead
            return {'ok': True, 'dead': dead, 'departed': departed}, b''
        if cmd == 'metrics':
            # fleet aggregation: the whole process registry snapshot —
            # the caller merges snapshots rid-deduped (in-process peers
            # share one registry and must not be double-counted)
            return {'ok': True,
                    'metrics': _tmetrics.default_registry().snapshot()}, \
                b''
        if cmd == 'telemetry':
            # flight-recorder sweep for the cross-process trace export
            return {'ok': True,
                    'telemetry': _trace.snapshot_buffer()}, b''
        if cmd == 'mesh_join':
            with self._lock:
                self._mesh_members[int(header['rank'])] = {
                    'joined': self._clock(),
                    'meta': header.get('meta') or {}}
                self._mesh_gen += 1
                _G_MESH_GEN.set(self._mesh_gen)
                return {'ok': True, 'gen': self._mesh_gen,
                        'members': sorted(self._mesh_members)}, b''
        if cmd == 'mesh_leave':
            with self._lock:
                if self._mesh_members.pop(int(header['rank']),
                                          None) is not None:
                    self._mesh_gen += 1
                    _G_MESH_GEN.set(self._mesh_gen)
                return {'ok': True, 'gen': self._mesh_gen,
                        'members': sorted(self._mesh_members)}, b''
        if cmd == 'mesh_epoch':
            # leader-driven re-formation: eject dead members and bump
            # the generation ONCE. Ejecting an already-gone rank is a
            # no-op (idempotent — a retried epoch does not double-bump),
            # so the fence moves exactly one step per real reformation.
            with self._lock:
                changed = False
                for r in header.get('eject') or []:
                    if self._mesh_members.pop(int(r), None) is not None:
                        changed = True
                if changed or header.get('bump'):
                    self._mesh_gen += 1
                    _G_MESH_GEN.set(self._mesh_gen)
                return {'ok': True, 'gen': self._mesh_gen,
                        'members': sorted(self._mesh_members)}, b''
        return self._handle_app(header, payload, peer)

    def _handle_app(self, header, payload, peer):
        """Application commands — subclasses implement; reached only
        for commands the base protocol does not answer."""
        return {'ok': False,
                'error': f'unknown cmd {header["cmd"]!r}'}, b''


class RpcClient:
    """One retrying channel to one :class:`RpcServer` address.

    Extracted from ``KVStoreDistAsync._rpc_to``: transport failures
    (``ConnectionError``/``OSError``/timeouts, fault-injected ones
    included) close and re-dial the socket, then resend with
    exponential backoff + jitter until the attempt budget or per-call
    deadline runs out. A half-written request or half-read reply can
    never desync the stream because the socket is dropped on EVERY
    failure. Application-level errors (``ok: False`` replies) are NOT
    retried — they raise ``RuntimeError``.

    The channel stamps nothing into headers: (rank, client, seq)
    identity belongs to the caller, which may need to keep it stable
    across channels (router failover re-sends the SAME identity to a
    different replica).
    """

    def __init__(self, host, port, label=None, what='dist_async',
                 retries=None, deadline_s=None, backoff_s=None,
                 stats=None):
        self._host, self._port = host, int(port)
        self._label = label if label is not None \
            else f'server at {host}:{port}'
        self._what = what
        self._sock = None
        self._sock_lock = threading.Lock()
        env = os.environ.get
        self._retries = int(env('MXNET_KVSTORE_RPC_RETRIES', '4')) \
            if retries is None else int(retries)
        self._deadline = float(env('MXNET_KVSTORE_RPC_DEADLINE_S', '60')) \
            if deadline_s is None else float(deadline_s)
        self._backoff = float(env('MXNET_KVSTORE_RPC_BACKOFF_S', '0.05')) \
            if backoff_s is None else float(backoff_s)
        self._stats = stats if stats is not None \
            else {'retries': 0, 'redials': 0, 'giveups': 0}

    @property
    def addr(self):
        return (self._host, self._port)

    @property
    def stats(self):
        return self._stats

    def _dial(self, deadline=None):
        """Connect with bounded patience: the startup path keeps the
        historical ~10s budget; reconnects inside a retrying RPC pass
        the caller's remaining ``deadline`` (monotonic timestamp)."""
        import time
        last = None
        for _ in range(100):
            if deadline is not None and time.monotonic() >= deadline:
                break
            try:
                s = socket.create_connection(
                    (self._host, self._port), timeout=5)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # per-call timeouts are managed by call() from its
                # deadline; an unset timeout here would otherwise cap
                # every recv (barriers included) at connect's 5s
                s.settimeout(None)
                return s
            except OSError as e:
                last = e
                time.sleep(0.1)
        raise ConnectionError(
            f'cannot reach {self._what} {self._label} at '
            f'{self._host}:{self._port}: {last}')

    def connect(self):
        """Eagerly establish the connection (startup-time fail-fast)."""
        with self._sock_lock:
            if self._sock is None:
                self._sock = self._dial()
        return self

    def sock(self):
        """The live socket (diagnostics, e.g. getsockname), or None."""
        return self._sock

    def close(self):
        with self._sock_lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def call(self, header, payload=b'', attempts=None, deadline_s=None):
        """One RPC with retry/backoff + reconnect (see class docs).

        When the calling thread has a live trace context the whole
        call (retries and backoff included) becomes an ``rpc:<cmd>``
        span and the envelope grows an optional ``tc`` field carrying
        that span's context — old peers ignore the extra key, tracing
        peers adopt it, so one user request stitches into ONE trace
        across every hop. No context → the envelope is byte-identical
        to the pre-telemetry wire format."""
        if _trace.current_tc() is None:
            return self._call(header, payload, attempts, deadline_s)
        with _trace.span('rpc:%s' % header['cmd'], peer=self._label):
            header = dict(header)
            header['tc'] = _trace.current_tc()
            return self._call(header, payload, attempts, deadline_s)

    def _call(self, header, payload=b'', attempts=None, deadline_s=None):
        import random
        import time
        deadline = time.monotonic() + (
            self._deadline if deadline_s is None else deadline_s)
        if attempts is None:
            attempts = max(1, self._retries + 1)
        with self._sock_lock:
            for attempt in range(attempts):
                try:
                    sock = self._sock
                    if sock is None:
                        sock = self._dial(deadline=deadline)
                        self._sock = sock
                        self._stats['redials'] += 1
                        _C_REDIALS.inc()
                    sock.settimeout(
                        max(0.05, deadline - time.monotonic()))
                    _send_msg(sock, header, payload)
                    reply, rpayload = _recv_msg(sock)
                    sock.settimeout(None)
                    break
                except (ConnectionError, TimeoutError, OSError) as e:
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                    self._sock = None
                    now = time.monotonic()
                    if attempt + 1 >= attempts or now >= deadline:
                        self._stats['giveups'] += 1
                        _C_GIVEUPS.inc()
                        raise ConnectionError(
                            f'{self._what} rpc {header["cmd"]!r} to '
                            f'{self._label} at '
                            f'{self._host}:{self._port} failed '
                            f'after {attempt + 1} attempt(s) '
                            f'({type(e).__name__}: {e}); raise '
                            'MXNET_KVSTORE_RPC_RETRIES / '
                            'MXNET_KVSTORE_RPC_DEADLINE_S to wait '
                            'longer') from e
                    self._stats['retries'] += 1
                    _C_RETRIES.inc()
                    step = self._backoff * (2 ** attempt)
                    step *= 0.5 + random.random() / 2   # jitter
                    time.sleep(min(step, max(0.0, deadline - now)))
        if not reply.get('ok'):
            err = RuntimeError(reply.get('error', 'rpc failed'))
            # the full reply rides along so callers can rehydrate typed
            # errors (the serve router maps reply['kind'] back to the
            # ServeError subclass the replica raised)
            err.reply = reply
            raise err
        return reply, rpayload
