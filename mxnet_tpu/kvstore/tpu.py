"""``dist_tpu_sync`` — multi-host KVStore over XLA collectives.

This is the BASELINE.json north-star component: the replacement for the
entire ps-lite stack (kvstore_dist.h:44, kvstore_dist_server.h:155 — worker/
server/scheduler processes, ZMQ vans, explicit key sharding). Design:

* one JAX process per host, joined via ``jax.distributed.initialize``
  (rendezvous ≙ the reference's DMLC_PS_ROOT_URI env protocol, but handled
  by the TPU runtime);
* ``pushpull`` = a jitted global mean/sum over all processes' arrays —
  lowered by XLA to an ICI allreduce within a slice and DCN collectives
  across slices. There are no servers: every host holds the full reduced
  value afterwards (allreduce-DP, the Horovod topology, but on ICI).
* sync is implicit in SPMD — ``barrier`` maps to a trivial collective.

Single-process fallback: with one process this degrades exactly to
KVStoreLocal semantics, so CI (8 virtual CPU devices) exercises the same
code path the pod runs.
"""

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray
from .base import register
from .kvstore import KVStoreLocal, _group, _reduce


@register
class KVStoreTPUSync(KVStoreLocal):
    """dist_tpu_sync / dist_sync: cross-host synchronous allreduce."""

    NAME = 'dist_tpu_sync'

    def __init__(self):
        super().__init__()
        self._nproc = jax.process_count()
        self._mesh = None
        if self._nproc > 1:
            devs = jax.devices()
            self._mesh = jax.sharding.Mesh(devs, ('dp',))

    def _allreduce(self, local_sum, key=None):
        """Global sum across processes. The gather crosses DCN once per
        tensor; the reduction itself runs on device. (The ICI-optimal
        single-collective path is the SPMD trainer —
        parallel.make_sharded_train_step — where XLA owns the allreduce;
        this KVStore surface keeps the reference's per-key semantics.)

        With 2-bit gradient compression enabled (set_gradient_compression,
        reference kvstore_dist.h compressed path), the local gradient is
        quantized before the hop — 16x fewer bytes over DCN — and the
        dequantized values are summed; the quantization error stays in
        this worker's residual (error feedback)."""
        gc = self.gradient_compression
        if gc.active and key is not None:
            shape, dtype = local_sum.shape, local_sum.dtype
            words = gc.quantize(key, local_sum)
            if self._nproc == 1:
                return gc.dequantize(words, shape, dtype)
            from jax.experimental import multihost_utils
            gathered = multihost_utils.process_allgather(words)
            return gc.dequantize_sum(jnp.asarray(gathered), shape, dtype)
        if self._nproc == 1:
            return local_sum
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(local_sum)
        return jnp.asarray(gathered).sum(axis=0)

    def pushpull(self, key, value, out=None, priority=0):
        for k, vals in _group(key, value):
            merged = self._allreduce(_reduce(vals), key=k)
            if self._updater is not None:
                if k not in self._store:
                    raise ValueError(
                        f'pushpull with an updater requires key {k!r} to be '
                        'initialized first (init/broadcast)')
                self._updater(k, NDArray(merged), self._store[k])
                result = self._store[k]._data
            else:
                result = merged
            targets = ([o for kk, os in _group(key, out) if kk == k
                        for o in os] if out is not None else vals)
            for t in targets:
                t._rebind(result)

    def _bcast0(self, raw):
        """Rank-0's value to every process, as a host-local array.
        broadcast_one_to_all returns a global-spanning (fully replicated)
        jax.Array that plain device_get refuses; the local replica is
        read out via its addressable shard — one broadcast's worth of
        DCN traffic, not an allgather."""
        from jax.experimental import multihost_utils
        arr = multihost_utils.broadcast_one_to_all(raw)
        if getattr(arr, 'is_fully_addressable', True):
            return jnp.asarray(arr)
        return jnp.asarray(arr.addressable_data(0))

    def init(self, key, value):
        """Rank-0's value is authoritative (reference KVStoreDist::Init):
        hosts that seeded independently converge here."""
        super().init(key, value)
        if self._nproc > 1:
            for k, _ in _group(key, value):
                self._store[k]._rebind(self._bcast0(self._store[k]._data))

    def push(self, key, value, priority=0):
        for k, vals in _group(key, value):
            merged = self._allreduce(_reduce(vals), key=k)
            if self._updater is not None and k in self._store:
                self._updater(k, NDArray(merged), self._store[k])
            elif k in self._store:
                # accumulate, matching KVStoreLocal.push semantics
                self._store[k]._rebind(self._store[k]._data + merged)
            else:
                self._store[k] = NDArray(merged)

    def broadcast(self, key, value, out, priority=0):
        """Rank-0's value wins (reference KVStoreDist::Init semantics)."""
        if self._nproc > 1:
            for k, vals in _group(key, value):
                self._store[k] = NDArray(self._bcast0(vals[0]._data))
        else:
            self.init(key, value)
        self.pull(key, out=out, priority=priority)

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    def barrier(self):
        if self._nproc > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices('kvstore_barrier')

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Reference include/mxnet/kvstore.h:408 — the TPU runtime restarts
        the whole SPMD job on failure, so a reachable store has 0 dead."""
        return 0

    @property
    def type(self):
        return 'dist_tpu_sync'


@register
class Horovod(KVStoreTPUSync):
    """Horovod-compatible plugin surface (reference
    python/mxnet/kvstore/horovod.py:25) backed by the same XLA allreduce."""

    NAME = 'horovod'

    @property
    def local_rank(self):
        return jax.process_index()


@register
class BytePS(KVStoreTPUSync):
    """BytePS plugin surface (reference python/mxnet/kvstore/byteps.py:45)."""

    NAME = 'byteps'
