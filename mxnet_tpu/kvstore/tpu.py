"""``dist_tpu_sync`` — multi-host KVStore over XLA collectives.

This is the BASELINE.json north-star component: the replacement for the
entire ps-lite stack (kvstore_dist.h:44, kvstore_dist_server.h:155 — worker/
server/scheduler processes, ZMQ vans, explicit key sharding). Design:

* one JAX process per host, joined via ``jax.distributed.initialize``
  (rendezvous ≙ the reference's DMLC_PS_ROOT_URI env protocol, but handled
  by the TPU runtime);
* ``pushpull`` = a jitted global mean/sum over all processes' arrays —
  lowered by XLA to an ICI allreduce within a slice and DCN collectives
  across slices. There are no servers: every host holds the full reduced
  value afterwards (allreduce-DP, the Horovod topology, but on ICI).
* sync is implicit in SPMD — ``barrier`` maps to a trivial collective.

Single-process fallback: with one process this degrades exactly to
KVStoreLocal semantics, so CI (8 virtual CPU devices) exercises the same
code path the pod runs.
"""

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray
from .base import register
from .kvstore import KVStoreLocal, _group, _reduce


@register
class KVStoreTPUSync(KVStoreLocal):
    """dist_tpu_sync / dist_sync: cross-host synchronous allreduce."""

    NAME = 'dist_tpu_sync'

    def __init__(self):
        super().__init__()
        self._nproc = jax.process_count()
        self._mesh = None
        if self._nproc > 1:
            devs = jax.devices()
            self._mesh = jax.sharding.Mesh(devs, ('dp',))

    def _allreduce(self, local_sum, key=None):
        """Global sum across processes as a jitted device collective
        (fusion.CrossProcess.psum): XLA lowers it to reduce-scatter +
        all-gather over ICI/DCN — 2(N-1)/N x size bytes on the wire, no
        host round-trip, async-dispatched. Replaces the round-1
        per-key blocking ``process_allgather`` (N x size + host sync).

        With 2-bit gradient compression enabled (set_gradient_compression,
        reference kvstore_dist.h compressed path), the local gradient is
        quantized before the hop — 16x fewer bytes over DCN — and the
        gathered words are decoded + summed on device in one executable;
        the quantization error stays in this worker's residual (error
        feedback)."""
        from .fusion import CrossProcess
        gc = self.gradient_compression
        if gc.active and key is not None:
            shape, dtype = local_sum.shape, local_sum.dtype
            words = gc.quantize(key, local_sum)
            if self._nproc == 1:
                return gc.dequantize(words, shape, dtype)
            size = 1
            for d in shape:
                size *= int(d)
            vals = CrossProcess.get().compressed_sum(
                words, gc.threshold, size)
            return vals.reshape(shape).astype(dtype)
        if self._nproc == 1:
            return local_sum
        out = CrossProcess.get().psum(local_sum.reshape(-1))
        return out.reshape(local_sum.shape)

    def pushpull(self, key, value, out=None, priority=0):
        for k, vals in _group(key, value):
            merged = self._allreduce(_reduce(vals), key=k)
            if self._updater is not None:
                if k not in self._store:
                    raise ValueError(
                        f'pushpull with an updater requires key {k!r} to be '
                        'initialized first (init/broadcast)')
                self._updater(k, NDArray(merged), self._store[k])
                result = self._store[k]._data
            else:
                result = merged
            targets = ([o for kk, os in _group(key, out) if kk == k
                        for o in os] if out is not None else vals)
            for t in targets:
                t._rebind(result)

    # ------------------------------------------------------------ fused path
    def fused_pushpull(self, keys, values, outs=None, priorities=None):
        """Bucketed fused pushpull — the fast distributed data path.

        Replaces the reference's per-key ps-lite PushPullDefault
        (kvstore_dist.h:578) and the P3 priority scheduler
        (p3store_dist.h) with:

        1. ONE jitted executable summing every key's device replicas,
        2. priority-ordered coalescing into fusion buffers
           (``MXNET_KVSTORE_FUSION_BUFFER_MB``, default 64),
        3. one XLA collective per buffer (psum → reduce-scatter +
           all-gather on the wire; with 2-bit compression, all_gather of
           packed words + on-device decode-sum),
        4. jitted split + rebind.

        Every step is async-dispatched: buffers issued first (higher
        priority) enter the device stream first, overlapping with
        whatever compute is still in flight — the comm/compute overlap
        P3 existed for, without a scheduler thread.

        With an updater and >1 process the ZeRO-1 path runs instead:
        gradients are psum_scatter'd so each rank receives only the keys
        it owns, the updater runs ONCE per key globally (optimizer state
        sharded N-ways, reference server-side ApplyUpdates semantics),
        and fresh weights ride back on an all_gather. Disable with
        ``MXNET_KVSTORE_ZERO1=0`` to fall back to replicated updates.
        Note: like the reference's server-side states,
        ``save_optimizer_states`` is rank-local under ZeRO-1.
        """
        import os as _os
        n = len(keys)
        if n == 0:
            return
        vals_lists = [v if isinstance(v, (list, tuple)) else [v]
                      for v in values]
        merged = KVStoreLocal._merge_local(self, keys, vals_lists)
        order = list(range(n))
        if priorities is not None:
            order.sort(key=lambda i: -priorities[i])
        gc = self.gradient_compression
        if (self._updater is not None and self._nproc > 1
                and not gc.active
                and _os.environ.get('MXNET_KVSTORE_ZERO1', '1') == '1'
                and self._zero1_update(keys, merged, vals_lists, outs,
                                       order)):
            return
        if self._updater is not None and self._nproc > 1:
            # a key whose optimizer state was created under ZeRO-1 has
            # that state sharded on its owner rank only; silently
            # continuing with replicated updates (e.g. after toggling
            # MXNET_KVSTORE_ZERO1 or enabling compression mid-run)
            # would diverge from it
            self._guard_update_mode(keys, 'replicated')
        if self._nproc > 1 or gc.active:
            merged = self._bucketed_allreduce(keys, merged, order, gc)
        self._apply_merged(keys, merged, vals_lists, outs)

    def _guard_update_mode(self, keys, mode):
        """Pin each key's updater-state layout ('zero1' sharded vs
        'replicated') on first update; raise on a mid-run switch."""
        if not hasattr(self, '_update_mode'):
            self._update_mode = {}
        for k in keys:
            prev = self._update_mode.setdefault(k, mode)
            if prev != mode:
                raise RuntimeError(
                    f'kvstore key {k!r}: optimizer state was created '
                    f'under {prev!r} updates but this pushpull selected '
                    f'{mode!r} (MXNET_KVSTORE_ZERO1 toggled or gradient '
                    'compression enabled mid-run?). Switching layouts '
                    'mid-run silently abandons sharded state; restart '
                    'training with a consistent configuration.')

    def _bucketed_allreduce(self, keys, merged, order, gc):
        from . import fusion
        cp = fusion.CrossProcess.get() if self._nproc > 1 else None
        limit = fusion.fusion_buffer_bytes()
        out = list(merged)
        if gc.active:
            # per-key quantization first (residuals are per key,
            # reference gradient_compression.h error feedback)
            words = [gc.quantize(keys[i], out[i]) for i in range(len(keys))]
            if cp is None:
                for i in order:
                    out[i] = gc.dequantize(words[i], out[i].shape,
                                           out[i].dtype)
                return out
            # decode blows words back up 16x on device; keep buffers small
            wbytes = [4 * int(w.shape[0]) for w in words]
            for bucket in fusion.make_buckets(
                    [wbytes[i] for i in order], max(limit // 16, 1 << 20)):
                sel = [order[j] for j in bucket]
                wtot = sum(int(words[i].shape[0]) for i in sel)
                pad_to = fusion._padded_len(wtot)
                flat_w = fusion._concat_flat([words[i] for i in sel],
                                             pad_to)
                vals = cp.compressed_sum(flat_w, gc.threshold,
                                         pad_to * 16)
                shapes = tuple(tuple(int(d) for d in merged[i].shape)
                               for i in sel)
                offs, woff = [], 0
                for i in sel:
                    offs.append(woff * 16)
                    woff += int(words[i].shape[0])
                parts = fusion._split_flat(vals, shapes, tuple(offs))
                for i, p in zip(sel, parts):
                    out[i] = p if str(merged[i].dtype) == 'float32' \
                        else p.astype(merged[i].dtype)
            return out
        # shared bucket plan (fusion.plan_buckets): same pipeline as the
        # pure in-axis form proven overlapped by tools/overlap —
        # here each bucket's psum is its own async dispatch so priority
        # order carries into the device stream
        for sel, shapes, offs, pad_to in fusion.plan_buckets(
                out, order, limit):
            flat = fusion._concat_flat([out[i] for i in sel], pad_to)
            summed = cp.psum(flat)
            parts = fusion._split_flat(summed, shapes, offs)
            for i, p in zip(sel, parts):
                out[i] = p
        return out

    def _zero1_update(self, keys, merged, vals_lists, outs, order):
        """ZeRO-1 sharded optimizer-on-store. Returns False to make the
        caller fall back (mixed dtypes)."""
        import numpy as _onp
        from . import fusion
        dt = merged[0].dtype
        if any(m.dtype != dt for m in merged):
            return False
        self._guard_update_mode(keys, 'zero1')
        for k in keys:
            if k not in self._store:
                raise ValueError(
                    f'pushpull with an updater requires key {k!r} to be '
                    'initialized first (init/broadcast)')
        cp = fusion.CrossProcess.get()
        nproc, me = self._nproc, self.rank
        sizes = [int(_onp.prod(m.shape)) or 1 for m in merged]
        # ownership is pinned per key on first sight: recomputing it from
        # each call's transient key list would migrate keys (and orphan
        # their sharded optimizer state) whenever the key set changes,
        # e.g. when a layer is frozen mid-training. Deterministic across
        # ranks because every rank sees the same SPMD call sequence.
        if not hasattr(self, '_z1_owner'):
            self._z1_owner, self._z1_load = {}, [0] * nproc
        new = [i for i in range(len(keys)) if keys[i] not in self._z1_owner]
        for j, r in zip(new, fusion.assign_owners(
                [sizes[i] for i in new], nproc, load=self._z1_load)):
            self._z1_owner[keys[j]] = r
            self._z1_load[r] += sizes[j]
        owner = [self._z1_owner[k] for k in keys]
        _, seg_keys, lmax, layout = fusion.zero1_layout(
            sizes, nproc, owner=owner, order=order)
        my_tile = cp.reduce_scatter(fusion._pack_segments(merged, layout))
        mine = seg_keys[me]
        if mine:
            myshapes = tuple(tuple(int(d) for d in merged[i].shape)
                             for i in mine)
            myoffs = tuple(int(o) for o in _onp.cumsum(
                [0] + [sizes[i] for i in mine[:-1]]))
            grads = fusion._split_flat(my_tile, myshapes, myoffs)
            for i, g in zip(mine, grads):
                self._updater(keys[i], NDArray(g), self._store[keys[i]])
            w_tile = fusion._concat_flat(
                [self._store[keys[i]]._data for i in mine], lmax)
        else:
            w_tile = jnp.zeros((lmax,), dt)
        full = cp.all_gather(w_tile)
        shapes, offs = [], []
        for i in range(len(keys)):
            shapes.append(tuple(int(d) for d in merged[i].shape))
            r = owner[i]
            off = r * lmax + sum(sizes[j] for j in seg_keys[r]
                                 [:seg_keys[r].index(i)])
            offs.append(int(off))
        parts = fusion._split_flat(full, tuple(shapes), tuple(offs))
        for i, k in enumerate(keys):
            self._store[k]._rebind(parts[i])
            targets = (outs[i] if outs is not None else vals_lists[i])
            if not isinstance(targets, (list, tuple)):
                targets = [targets]
            for t in targets:
                t._rebind(parts[i])
        return True

    def _bcast0(self, raw):
        """Rank-0's value to every process, as a host-local array.
        broadcast_one_to_all returns a global-spanning (fully replicated)
        jax.Array that plain device_get refuses; the local replica is
        read out via its addressable shard — one broadcast's worth of
        DCN traffic, not an allgather."""
        from jax.experimental import multihost_utils
        arr = multihost_utils.broadcast_one_to_all(raw)
        if getattr(arr, 'is_fully_addressable', True):
            return jnp.asarray(arr)
        return jnp.asarray(arr.addressable_data(0))

    def init(self, key, value):
        """Rank-0's value is authoritative (reference KVStoreDist::Init):
        hosts that seeded independently converge here."""
        super().init(key, value)
        if self._nproc > 1:
            for k, _ in _group(key, value):
                self._store[k]._rebind(self._bcast0(self._store[k]._data))

    def push(self, key, value, priority=0):
        for k, vals in _group(key, value):
            merged = self._allreduce(_reduce(vals), key=k)
            if self._updater is not None and k in self._store:
                self._updater(k, NDArray(merged), self._store[k])
            elif k in self._store:
                # accumulate, matching KVStoreLocal.push semantics
                self._store[k]._rebind(self._store[k]._data + merged)
            else:
                self._store[k] = NDArray(merged)

    def broadcast(self, key, value, out, priority=0):
        """Rank-0's value wins (reference KVStoreDist::Init semantics)."""
        if self._nproc > 1:
            for k, vals in _group(key, value):
                self._store[k] = NDArray(self._bcast0(vals[0]._data))
        else:
            self.init(key, value)
        self.pull(key, out=out, priority=priority)

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    def barrier(self):
        if self._nproc > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices('kvstore_barrier')

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Reference include/mxnet/kvstore.h:408 — the TPU runtime restarts
        the whole SPMD job on failure, so a reachable store has 0 dead."""
        return 0

    @property
    def type(self):
        return 'dist_tpu_sync'


# The Horovod / BytePS plugin classes (delegation shells with
# COMPAT-ALIAS fallback over this store) live in plugins.py.
