"""Gradient fusion buffers + cross-process device collectives.

This is the transport under ``dist_tpu_sync``'s fused push/pull: the
TPU-native replacement for the reference's ps-lite data path
(``src/kvstore/kvstore_dist.h:578`` PushPullDefault — per-key ZPushPull to
sharded servers) and its priority scheduler (``src/kvstore/p3store_dist.h``
slice-and-schedule). Design:

* **Fusion buffers** (Horovod-style, reference analog: the bigarray
  splitting bound ``MXNET_KVSTORE_BIGARRAY_BOUND`` inverted): many small
  parameters are coalesced into a handful of flat buffers so the wire sees
  a few large collectives instead of hundreds of key-sized ones. Buffer
  cap via ``MXNET_KVSTORE_FUSION_BUFFER_MB`` (default 64).
* **Device collectives, not host gathers**: the cross-process hop is a
  jitted ``shard_map``/``psum`` over a one-device-per-process mesh — XLA
  lowers it to ICI/DCN reduce-scatter + all-gather, so bytes on the wire
  are 2(N-1)/N x size and nothing round-trips through host RAM (the old
  path was a blocking ``process_allgather`` per key: N x size bytes +
  a host sync per parameter).
* **Async by construction**: every step (concat, collective, split) is a
  jitted dispatch; nothing blocks until a consumer reads. Buckets issued
  first (higher priority) enter the device stream first — the
  comm/compute overlap the reference's P3 priority machinery existed for.
* **ZeRO-1 sharded update** (``reduce_scatter_update``): when the
  optimizer runs "on the store" (reference server-side update,
  ``kvstore_dist_server.h`` ApplyUpdates), keys are round-robined across
  ranks; gradients are psum_scatter'd so each rank receives only the
  summed slices for keys it owns, runs the updater ONCE per key globally,
  and the fresh weights ride back on an all_gather. Same 2(N-1)/N bytes
  as allreduce, but optimizer compute and state are sharded N-ways.

Compile-cache hygiene: flat buffers are zero-padded to 64K-element
multiples so different models reuse the same executables.
"""

import os
from functools import partial

import numpy as _np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _shard_map(**kw):
    """jax.shard_map across versions (same shim as parallel.mesh, inlined
    so importing kvstore does not drag in the whole parallel package)."""
    if hasattr(jax, 'shard_map'):
        return partial(jax.shard_map, check_vma=False, **kw)
    from jax.experimental.shard_map import shard_map  # pragma: no cover
    return partial(shard_map, check_rep=False, **kw)


_PAD_QUANTUM = 65536  # elements; bounds the number of distinct jit shapes


def fusion_buffer_bytes():
    """Bucket cap in bytes — also the small-collective lint threshold
    (mx.analysis): a standalone collective under this size indicates an
    unbucketed push that make_buckets would have coalesced."""
    return int(float(os.environ.get('MXNET_KVSTORE_FUSION_BUFFER_MB', '64'))
               * 1e6)


def make_buckets(nbytes, limit):
    """Greedy in-order bucketing: consecutive keys share a bucket until
    `limit` bytes. Order is preserved so priority ordering of the caller
    carries straight into dispatch order."""
    buckets, cur, acc = [], [], 0
    for i, b in enumerate(nbytes):
        if cur and acc + b > limit:
            buckets.append(cur)
            cur, acc = [], 0
        cur.append(i)
        acc += b
    if cur:
        buckets.append(cur)
    return buckets


def _padded_len(n):
    return -(-n // _PAD_QUANTUM) * _PAD_QUANTUM


def plan_buckets(arrs, order, limit):
    """The store's bucket plan, shared by every fused transport: dtype-
    grouped (a flat buffer holds one dtype), order-preserving (the
    caller's priority order carries into dispatch order), greedy by
    bytes up to ``limit``. Yields ``(sel, shapes, offsets, pad_to)`` per
    bucket — exactly what _concat_flat/_split_flat consume."""
    by_dtype = {}
    for i in order:
        by_dtype.setdefault(str(arrs[i].dtype), []).append(i)
    for idxs in by_dtype.values():
        itemsize = arrs[idxs[0]].dtype.itemsize
        sizes = [int(_np.prod(arrs[i].shape)) or 1 for i in idxs]
        for bucket in make_buckets([s * itemsize for s in sizes], limit):
            sel = [idxs[j] for j in bucket]
            szs = [sizes[idxs.index(i)] for i in sel]
            shapes = tuple(tuple(int(d) for d in arrs[i].shape)
                           for i in sel)
            offs = tuple(int(o) for o in _np.cumsum([0] + szs[:-1]))
            yield sel, shapes, offs, _padded_len(sum(szs))


def zero1_layout(sizes, nproc, owner=None, order=None):
    """The ZeRO-1 flat-tile layout, derived once for every consumer
    (the eager store's _zero1_update, the pure in-axis form below, and
    any caller sizing a sharded optimizer-state tile): per-key owners,
    per-rank key segments (in ``order`` — the caller's priority order —
    when given), the padded tile length, and the _pack_segments layout
    tuple. Returns ``(owner, seg_keys, lmax, layout)``."""
    owner = assign_owners(sizes, nproc) if owner is None else owner
    order = range(len(sizes)) if order is None else order
    seg_keys = [[i for i in order if owner[i] == r]
                for r in range(nproc)]
    seg_len = [sum(sizes[i] for i in s) for s in seg_keys]
    lmax = _padded_len(max(seg_len + [1]))
    layout = tuple((tuple(s), lmax - seg_len[r])
                   for r, s in enumerate(seg_keys))
    return owner, seg_keys, lmax, layout


def zero1_update_in_axis(grads, weights, mom_tile, axis_name, nproc,
                         update_fn, owner=None):
    """Pure, named-axis form of the ZeRO-1 sharded update — the device
    math of ``KVStoreTPUSync._zero1_update`` (the default Trainer path
    with an updater and >1 process) for use INSIDE a shard_map'd
    program: the same ``assign_owners``/``_pack_segments`` layout, ONE
    ``psum_scatter`` delivering each owner its summed gradient tile,
    the optimizer update on the owned tile only (state sharded N-ways),
    and ONE ``all_gather`` returning fresh weights — 2(N-1)/N wire
    bytes total, identical to allreduce, with optimizer compute 1/N.

    ``update_fn(w_tile, g_tile, mom_tile) -> (new_w_tile, new_mom_tile)``
    — elementwise optimizers (the sgd/adam families) are concatenation-
    invariant, so the flat-tile update equals the eager store's per-key
    update. Returns ``(new_weights_per_key, new_mom_tile)``.
    tools/overlap/aot_overlap.py compiles this on a v5e topology: the
    scheduled HLO shows optimizer compute between the two collectives.
    """
    sizes = [int(_np.prod(w.shape)) or 1 for w in weights]
    owner, seg_keys, lmax, layout = zero1_layout(sizes, nproc, owner)
    g_tile = jax.lax.psum_scatter(_pack_segments(list(grads), layout),
                                  axis_name, tiled=True)
    packed_w = _pack_segments(list(weights), layout)
    r = jax.lax.axis_index(axis_name)
    w_tile = jax.lax.dynamic_slice_in_dim(packed_w, r * lmax, lmax)
    new_w, new_m = update_fn(w_tile, g_tile, mom_tile)
    full = jax.lax.all_gather(new_w, axis_name, tiled=True)
    outs = []
    for i in range(len(weights)):
        ro = owner[i]
        off = ro * lmax + sum(sizes[j] for j in
                              seg_keys[ro][:seg_keys[ro].index(i)])
        outs.append(jax.lax.dynamic_slice_in_dim(
            full, off, sizes[i]).reshape(weights[i].shape))
    return outs, new_m


def bucketed_allreduce_in_axis(raws, axis_name, limit=None, order=None):
    """Pure, named-axis form of the fused-pushpull device math, for use
    INSIDE a shard_map'd/pjit'd program: the same plan_buckets/
    _concat_flat/_split_flat pipeline KVStoreTPUSync._bucketed_allreduce
    dispatches per bucket (with CrossProcess.psum as the collective),
    but with ``lax.psum(.., axis_name)`` so an entire train step —
    forward, backward, bucketed gradient allreduce, optimizer update —
    compiles as ONE program. tools/overlap/aot_overlap.py compiles this
    exact function on a v5e topology and checks the scheduled HLO
    interleaves the bucket collectives with backward compute."""
    limit = fusion_buffer_bytes() if limit is None else limit
    out = list(raws)
    order = list(range(len(out))) if order is None else order
    for sel, shapes, offs, pad_to in plan_buckets(out, order, limit):
        flat = _concat_flat([out[i] for i in sel], pad_to)
        summed = jax.lax.psum(flat, axis_name)
        parts = _split_flat(summed, shapes, offs)
        for i, p in zip(sel, parts):
            out[i] = p
    return out


@jax.jit
def _fused_replica_sum(raws_lists):
    """Sum each key's device replicas — all keys in ONE executable
    (reference CommDevice::Reduce per key, comm.h:452, here batched)."""
    out = []
    for rs in raws_lists:
        out.append(rs[0] if len(rs) == 1
                   else jnp.sum(jnp.stack(rs), axis=0))
    return out


@partial(jax.jit, static_argnames=('pad_to',))
def _concat_flat(raws, pad_to):
    flat = jnp.concatenate([r.reshape(-1) for r in raws]) if len(raws) > 1 \
        else raws[0].reshape(-1)
    n = flat.shape[0]
    if pad_to > n:
        flat = jnp.pad(flat, ((0, pad_to - n),))
    return flat


@partial(jax.jit, static_argnames=('shapes', 'offsets'))
def _split_flat(flat, shapes, offsets):
    out = []
    for shape, off in zip(shapes, offsets):
        n = int(_np.prod(shape)) if shape else 1
        out.append(jax.lax.dynamic_slice_in_dim(flat, off, n).reshape(shape))
    return out


@partial(jax.jit, static_argnames=('layout',))
def _pack_segments(raws, layout):
    """Rank-major flat packing for the ZeRO-1 update: ``layout`` is a
    tuple over ranks of (key-index tuple, zero-pad) so psum_scatter's
    tile i lands exactly on rank i's owned keys."""
    dt = raws[0].dtype
    segs = []
    for idxs, pad in layout:
        parts = [raws[i].reshape(-1) for i in idxs]
        seg = jnp.concatenate(parts) if parts else jnp.zeros((0,), dt)
        if pad:
            seg = jnp.pad(seg, ((0, pad),))
        segs.append(seg)
    return jnp.concatenate(segs)


class CrossProcess:
    """Cached jitted collectives over a one-device-per-process mesh.

    The mesh axis spans *processes* (hosts), matching the reference's
    worker set (``ps::Postoffice`` node group); within a process the
    replica reduce has already happened on device.
    """

    _instance = None

    @classmethod
    def get(cls):
        if cls._instance is None or \
                cls._instance._nproc != jax.process_count():
            cls._instance = cls()
        return cls._instance

    def __init__(self):
        self._nproc = jax.process_count()
        me = jax.process_index()
        per_proc = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        devs = [per_proc[p] for p in sorted(per_proc)]
        self._mesh = Mesh(_np.array(devs), ('dp',))
        self._local_dev = per_proc[me]
        self._fns = {}

    # ------------------------------------------------------------- plumbing
    def _to_global(self, flat):
        """Wrap this process's flat contribution as a shard of a global
        [nproc*L] array — a device-side handoff, no host copy."""
        L = flat.shape[0]
        sh = NamedSharding(self._mesh, P('dp'))
        local = jax.device_put(flat, self._local_dev)
        return jax.make_array_from_single_device_arrays(
            (self._nproc * L,), sh, [local])

    @staticmethod
    def _local(out):
        return out.addressable_data(0)

    # ----------------------------------------------------------- collectives
    def psum(self, flat):
        """Allreduce: every process gets sum over processes of `flat`.
        XLA lowers the psum to reduce-scatter + all-gather over ICI/DCN."""
        L, dt = flat.shape[0], str(flat.dtype)
        key = ('psum', L, dt)
        fn = self._fns.get(key)
        if fn is None:
            fn = jax.jit(_shard_map(
                mesh=self._mesh, in_specs=P('dp'), out_specs=P('dp'))(
                    lambda x: jax.lax.psum(x, 'dp')))
            self._fns[key] = fn
        return self._local(fn(self._to_global(flat)))

    def reduce_scatter(self, flat):
        """Each process gets its own 1/nproc tile of the global sum —
        the grad half of the ZeRO-1 update. `flat` length must be a
        multiple of nproc."""
        L, dt = flat.shape[0], str(flat.dtype)
        assert L % self._nproc == 0
        key = ('rs', L, dt)
        fn = self._fns.get(key)
        if fn is None:
            fn = jax.jit(_shard_map(
                mesh=self._mesh, in_specs=P('dp'), out_specs=P('dp'))(
                    lambda x: jax.lax.psum_scatter(x, 'dp', tiled=True)))
            self._fns[key] = fn
        return self._local(fn(self._to_global(flat)))

    def all_gather(self, tile):
        """Inverse of reduce_scatter: concatenate every process's tile —
        the weight half of the ZeRO-1 update."""
        L, dt = tile.shape[0], str(tile.dtype)
        key = ('ag', L, dt)
        fn = self._fns.get(key)
        if fn is None:
            fn = jax.jit(_shard_map(
                mesh=self._mesh, in_specs=P('dp'), out_specs=P('dp'))(
                    lambda x: jax.lax.all_gather(x, 'dp', tiled=True)))
            self._fns[key] = fn
        # every shard holds the full concat; read ours
        return self._local(fn(self._to_global(tile)))

    def compressed_sum(self, words, threshold, n_values):
        """2-bit path: all_gather the packed words (16x fewer bytes on the
        wire — the whole point of compression, reference
        gradient_compression.h), then decode + sum on device in the same
        executable."""
        W = words.shape[0]
        key = ('gc', W, n_values)
        fn = self._fns.get(key)
        if fn is None:
            def body(w, thr):
                gathered = jax.lax.all_gather(w, 'dp')  # [nproc, W]
                shifts = jnp.arange(16, dtype=jnp.uint32) * 2
                codes = (gathered[:, :, None] >> shifts) & jnp.uint32(3)
                vals = jnp.where(codes == 3, thr,
                                 jnp.where(codes == 2, -thr, 0.0))
                return vals.reshape(gathered.shape[0], -1).sum(axis=0)

            fn = jax.jit(_shard_map(
                mesh=self._mesh, in_specs=(P('dp'), P()),
                out_specs=P('dp'))(body))
            self._fns[key] = fn
        thr = jnp.float32(threshold)
        return self._local(fn(self._to_global(words), thr))[:n_values]


def assign_owners(sizes, nproc, load=None):
    """Deterministic balanced assignment of keys to owner ranks for the
    ZeRO-1 update (largest-first greedy onto the least-loaded rank,
    optionally seeded with existing per-rank `load`). Every rank computes
    the same assignment — no coordination needed."""
    order = sorted(range(len(sizes)), key=lambda i: -sizes[i])
    load = list(load) if load is not None else [0] * nproc
    owner = [0] * len(sizes)
    for i in order:
        r = min(range(nproc), key=lambda j: load[j])
        owner[i] = r
        load[r] += sizes[i]
    return owner
