"""Deterministic fault injection for the ``dist_async`` transport.

The resilient RPC layer (retry/backoff + reconnect in
``dist_async._rpc_to``, server-side seq dedup) is only trustworthy if
every recovery path can be driven on demand — real network chaos is
neither deterministic nor CI-friendly. This module hooks the two wire
functions (``_send_msg``/``_recv_msg``) and injects faults according to
a spec, so a connection reset mid-push or a lossy link is an ordinary
in-process test case (the reference stack gets the same effect from
ps-lite's ``PS_DROP_MSG`` resender knob; here the injection is exact
and counted).

Spec grammar — ``MXNET_KVSTORE_FAULT_SPEC`` or
:func:`configure`, semicolon-separated rules::

    drop:CMD:P[:seed=N]     with probability P (seeded RNG, default
                            seed 0 — deterministic sequence), fail a
                            matching request send with
                            ConnectionResetError BEFORE any byte
                            leaves: the message is lost pre-delivery,
                            so a retry re-executes it.
    delay:CMD:DUR           sleep DUR (``50ms``, ``0.2s``, or bare
                            seconds) before a matching send.
    reset_after[:CMD]:N     the N-th matching request is DELIVERED and
                            applied, then the connection is reset
                            before its reply is read — the
                            lost-reply-after-apply case that the
                            (rank, client, seq) dedup window must
                            absorb. Fires once.
    reset_every[:CMD]:N     same, but every N-th matching request
                            (soak mode).
    die_after[:CMD]:N       the N-th matching request raises
                            :class:`InjectedWorkerDeath` BEFORE any
                            byte leaves — the worker-process-kill
                            case. Deliberately NOT a transport error,
                            so the retry loop does not absorb it: it
                            propagates to the training loop, which
                            "dies" (elastic chaos tests). Fires once.
    kill_host[:CMD]:N       the N-th matching request raises
                            :class:`InjectedHostDeath` — the whole-host
                            failure case for pod-mesh chaos tests.
                            Pair with ``rank=R`` to kill exactly one
                            emulated host; like ``die_after`` it is a
                            RuntimeError the retry loop must not
                            absorb, but mesh drivers can tell the two
                            apart (host death takes all of the host's
                            devices out of the mesh). Fires once.
    partition[:CMD]:N:M     requests N .. N+M-1 (counted over matching
                            sends) raise ConnectionResetError — a
                            transient network partition of a mesh
                            member that heals after M failed attempts.
                            Count-based, so the chaos tests need no
                            wall-clock sleeps.

``CMD`` filters on the wire command (``push``, ``pull``, ``init``,
``ping``, ``barrier``, ...); ``*`` matches any worker request. Server
replies carry no ``cmd`` field and only match the literal filter
``reply``, so a cmd-less rule can never fire on the server's side of
an in-process test. Any rule takes a ``rank=R`` option restricting it
to requests stamped with that worker rank (e.g.
``die_after:push:3:rank=1`` kills worker 1 on its 3rd push) — requests
without a rank stamp never match a ranked rule.

Counters from :func:`injected` (``{'drop': n, 'delay': n, 'reset': n,
'die': n, 'total': n}``) are folded into the server's ``stats`` RPC
reply by ``_AsyncServer``, so assertions can read injection and apply
counts through one call (``KVStoreDistAsync.server_health``).

The plan is process-global (both ends of an in-process loopback pair
see it) but rules target the worker side via the ``cmd`` filter; the
pending-reset flag is thread-local so a reset armed by one store's
send can only fire on that same thread's reply read.
"""

import os
import random
import re
import threading
import time

__all__ = ['configure', 'clear', 'active', 'injected',
           'on_send', 'on_recv', 'FaultSpecError', 'InjectedWorkerDeath',
           'InjectedHostDeath']


class FaultSpecError(ValueError):
    """Malformed ``MXNET_KVSTORE_FAULT_SPEC`` rule."""


class InjectedWorkerDeath(RuntimeError):
    """Raised by a ``die_after`` rule: simulates the worker process
    dying at this exact send. A RuntimeError (not ConnectionError /
    OSError) on purpose — the RPC retry loop must NOT catch it, the
    worker's training loop must."""


class InjectedHostDeath(InjectedWorkerDeath):
    """Raised by a ``kill_host`` rule: the whole emulated host (its
    kvstore rank AND all devices it owns) dies at this exact send.
    Subclasses :class:`InjectedWorkerDeath` so generic elastic
    handling still applies, while pod-mesh drivers can distinguish a
    host loss (mesh must re-form on fewer devices) from a lone worker
    death."""


def _parse_duration(text):
    m = re.fullmatch(r'(\d+(?:\.\d+)?)(ms|s)?', text)
    if not m:
        raise FaultSpecError(f'bad duration {text!r} (want e.g. 50ms, 0.2s)')
    val = float(m.group(1))
    return val / 1e3 if m.group(2) == 'ms' else val


class _Rule:
    def __init__(self, action, cmd, **kw):
        self.action = action
        self.cmd = cmd            # None == any worker request
        self.rank = None          # None == any rank
        self.seen = 0             # matching sends so far (reset_* counting)
        self.__dict__.update(kw)

    def matches(self, cmd, rank=None):
        if self.rank is not None and rank != self.rank:
            return False
        if self.cmd is None or self.cmd == '*':
            # wildcard: any worker REQUEST, never a server reply
            return cmd != 'reply'
        return self.cmd == cmd


def _parse_rule(text):
    parts = text.split(':')
    action = parts[0].strip()
    opts = {}
    while parts and '=' in parts[-1]:
        k, v = parts.pop().split('=', 1)
        opts[k.strip()] = v.strip()
    rule = None
    if action == 'drop':
        if len(parts) != 3:
            raise FaultSpecError(f'drop rule {text!r}: want drop:CMD:P')
        p = float(parts[2])
        if not 0.0 <= p <= 1.0:
            raise FaultSpecError(f'drop probability {p} outside [0, 1]')
        rule = _Rule('drop', parts[1], p=p,
                     rng=random.Random(int(opts.get('seed', 0))))
    elif action == 'delay':
        if len(parts) != 3:
            raise FaultSpecError(f'delay rule {text!r}: want delay:CMD:DUR')
        rule = _Rule('delay', parts[1], duration=_parse_duration(parts[2]))
    elif action in ('reset_after', 'reset_every', 'die_after',
                    'kill_host'):
        if len(parts) == 2:          # reset_after:N — any worker request
            cmd, n = None, parts[1]
        elif len(parts) == 3:        # reset_after:CMD:N
            cmd, n = parts[1], parts[2]
        else:
            raise FaultSpecError(
                f'{action} rule {text!r}: want {action}[:CMD]:N')
        n = int(n)
        if n < 1:
            raise FaultSpecError(f'{action} count must be >= 1, got {n}')
        rule = _Rule(action, cmd, n=n)
    elif action == 'partition':
        if len(parts) == 3:          # partition:N:M — any worker request
            cmd, n, m = None, parts[1], parts[2]
        elif len(parts) == 4:        # partition:CMD:N:M
            cmd, n, m = parts[1], parts[2], parts[3]
        else:
            raise FaultSpecError(
                f'partition rule {text!r}: want partition[:CMD]:N:M')
        n, m = int(n), int(m)
        if n < 1 or m < 1:
            raise FaultSpecError(
                f'partition start/width must be >= 1, got {n}:{m}')
        rule = _Rule('partition', cmd, n=n, m=m)
    else:
        raise FaultSpecError(
            f'unknown fault action {action!r} in rule {text!r} '
            "(know: drop, delay, reset_after, reset_every, die_after, "
            "kill_host, partition)")
    if 'rank' in opts:
        try:
            rule.rank = int(opts['rank'])
        except ValueError:
            raise FaultSpecError(
                f'rule {text!r}: rank= wants an integer, '
                f'got {opts["rank"]!r}')
    return rule


class FaultPlan:
    """A parsed spec plus its injection counters."""

    def __init__(self, spec):
        self.spec = spec
        self.rules = [_parse_rule(r) for r in spec.split(';')
                      if r.strip()]
        if not self.rules:
            raise FaultSpecError(f'empty fault spec {spec!r}')
        self.counts = {'drop': 0, 'delay': 0, 'reset': 0, 'die': 0,
                       'kill_host': 0, 'partition': 0}
        self._lock = threading.Lock()
        self._tls = threading.local()

    # ------------------------------------------------------------- hooks
    def on_send(self, header):
        cmd = header.get('cmd', 'reply')
        rank = header.get('rank')
        rank = int(rank) if rank is not None else None
        delay = 0.0
        for rule in self.rules:
            if not rule.matches(cmd, rank):
                continue
            if rule.action == 'die_after':
                with self._lock:
                    rule.seen += 1
                    fire = rule.seen == rule.n
                    if fire:
                        self.counts['die'] += 1
                if fire:
                    raise InjectedWorkerDeath(
                        f'fault-injected worker death on {cmd!r} rpc'
                        + (f' (rank {rank})' if rank is not None else ''))
            elif rule.action == 'kill_host':
                with self._lock:
                    rule.seen += 1
                    fire = rule.seen == rule.n
                    if fire:
                        self.counts['kill_host'] += 1
                if fire:
                    raise InjectedHostDeath(
                        f'fault-injected host death on {cmd!r} rpc'
                        + (f' (rank {rank})' if rank is not None else ''))
            elif rule.action == 'partition':
                with self._lock:
                    rule.seen += 1
                    fire = rule.n <= rule.seen < rule.n + rule.m
                    if fire:
                        self.counts['partition'] += 1
                if fire:
                    raise ConnectionResetError(
                        f'fault-injected partition of {cmd!r} rpc '
                        '(member unreachable; heals after '
                        f'{rule.m} attempts)')
            elif rule.action == 'delay':
                with self._lock:
                    self.counts['delay'] += 1
                delay += rule.duration
            elif rule.action == 'drop':
                with self._lock:
                    hit = rule.rng.random() < rule.p
                    if hit:
                        self.counts['drop'] += 1
                if hit:
                    raise ConnectionResetError(
                        f'fault-injected drop of {cmd!r} rpc '
                        '(message lost before delivery)')
            else:                      # reset_after / reset_every
                with self._lock:
                    rule.seen += 1
                    fire = (rule.seen == rule.n
                            if rule.action == 'reset_after'
                            else rule.seen % rule.n == 0)
                    if fire:
                        self.counts['reset'] += 1
                if fire:
                    # the request itself goes out — the reply read on
                    # THIS thread is what dies (lost-reply-after-apply)
                    self._tls.reset_recv = True
        if delay:
            time.sleep(delay)

    def on_recv(self, sock):
        if getattr(self._tls, 'reset_recv', False):
            self._tls.reset_recv = False
            try:
                # the peer's reply bytes may already sit in the buffer;
                # a real RST discards them, so must we — otherwise a
                # non-reconnecting reader would resync on a stale reply
                sock.close()
            except OSError:
                pass
            raise ConnectionResetError(
                'fault-injected connection reset before reply')

    def injected(self):
        with self._lock:
            out = dict(self.counts)
        out['total'] = sum(out.values())
        return out


_PLAN = None


def configure(spec=None):
    """Install a fault plan from ``spec`` (or, when ``None``, from
    ``MXNET_KVSTORE_FAULT_SPEC``). An empty spec clears the plan.
    Returns the active :class:`FaultPlan` or ``None``."""
    global _PLAN
    if spec is None:
        spec = os.environ.get('MXNET_KVSTORE_FAULT_SPEC', '')
    _PLAN = FaultPlan(spec) if spec.strip() else None
    return _PLAN


def clear():
    """Remove any active fault plan."""
    global _PLAN
    _PLAN = None


def active():
    """The installed :class:`FaultPlan`, or ``None``."""
    return _PLAN


def injected():
    """Injection counters of the active plan ({} when no plan)."""
    return _PLAN.injected() if _PLAN is not None else {}


def on_send(header):
    """Hook point for ``dist_async._send_msg`` (may raise or sleep)."""
    if _PLAN is not None:
        _PLAN.on_send(header)


def on_recv(sock):
    """Hook point for ``dist_async._recv_msg`` (may raise and close)."""
    if _PLAN is not None:
        _PLAN.on_recv(sock)


# a spec set in the environment before process start (the launcher
# path: tools/launch.py exports it to every worker) arms itself on
# first import; tests configure()/clear() explicitly
if os.environ.get('MXNET_KVSTORE_FAULT_SPEC'):
    configure()
