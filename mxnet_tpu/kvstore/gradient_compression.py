"""2-bit gradient compression with error feedback.

Reference: ``src/kvstore/gradient_compression.{h,cc}`` (+ kernel in
``gradient_compression-inl.h``): gradients are thresholded to
{-threshold, 0, +threshold} with the quantization error accumulated in a
per-key *residual* so nothing is lost over time; 16 values pack into one
32-bit word (2 bits each → 16x smaller than fp32). In the reference this
runs on the worker before the ps-lite push and after the pull
(``kvstore_dist.h`` compressed path); here it runs before the cross-host
gather in ``dist_tpu_sync`` — the one hop that crosses DCN — and both the
quantize and dequantize kernels are single fused XLA programs (bit packing
is a reshape + shift + bitwise-or reduction, which XLA vectorizes on the
VPU; no scalar loop like the reference's per-block CUDA kernel).

Codes: 0b11 → +threshold, 0b10 → -threshold, 0b00 → 0. Value j of a
16-value block occupies bits [2j, 2j+1] of its uint32 word.
"""

from functools import partial

import numpy as _np

import jax
import jax.numpy as jnp

__all__ = ['GradientCompression']

_BLOCK = 16  # values per uint32 word


@partial(jax.jit, static_argnames=('size',))
def _quantize_2bit(grad, residual, threshold, size):
    """Returns (packed uint32 words, new residual).

    Mirrors the reference update rule (gradient_compression-inl.h
    quantize_2bit::Map): acc = residual + grad; emit ±threshold when
    |acc| crosses it and subtract the emitted value from the residual.
    """
    acc = residual + grad
    q = jnp.where(acc >= threshold, threshold,
                  jnp.where(acc <= -threshold, -threshold, 0.0))
    new_residual = acc - q
    codes = jnp.where(acc >= threshold, jnp.uint32(3),
                      jnp.where(acc <= -threshold, jnp.uint32(2),
                                jnp.uint32(0)))
    pad = (-size) % _BLOCK
    codes = jnp.pad(codes.reshape(-1), ((0, pad),))
    blocks = codes.reshape(-1, _BLOCK)
    shifts = jnp.arange(_BLOCK, dtype=jnp.uint32) * 2
    # disjoint bit ranges → sum == bitwise-or, and sum reduces cleanly
    words = (blocks << shifts).sum(axis=1, dtype=jnp.uint32)
    return words, new_residual


@partial(jax.jit, static_argnames=('size',))
def _dequantize_2bit(words, threshold, size):
    shifts = jnp.arange(_BLOCK, dtype=jnp.uint32) * 2
    codes = (words[:, None] >> shifts) & jnp.uint32(3)
    vals = jnp.where(codes == 3, threshold,
                     jnp.where(codes == 2, -threshold, 0.0))
    return vals.reshape(-1)[:size]


class GradientCompression:
    """Per-kvstore compression state (reference GradientCompression class,
    gradient_compression.h:52). Residuals are kept per key, matching the
    reference where each worker owns one residual array per parameter."""

    def __init__(self):
        self.type = 'none'
        self.threshold = 0.5
        self._residuals = {}

    def set_params(self, compression_params):
        params = dict(compression_params or {})
        ctype = params.pop('type', 'none')
        if ctype not in ('none', '2bit'):
            raise ValueError(
                f'unsupported gradient compression type {ctype!r} '
                "(reference supports only '2bit', gradient_compression.h:37)")
        threshold = float(params.pop('threshold', 0.5))
        if ctype == '2bit' and threshold <= 0:
            raise ValueError('threshold must be positive')
        if params:
            raise ValueError(f'unknown compression params {sorted(params)}')
        self.type = ctype
        self.threshold = threshold
        self._residuals = {}

    @property
    def active(self):
        return self.type == '2bit'

    def get_compression_factor(self):
        """Reference GetCompressionFactor: fp32 → 2 bits = 16."""
        return 16 if self.active else 1

    def get_compressed_size(self, original_size):
        """Words needed for `original_size` floats, in bytes
        (reference GetCompressedSize)."""
        if not self.active:
            return original_size * 4
        return 4 * ((original_size + _BLOCK - 1) // _BLOCK)

    def quantize(self, key, grad):
        """Compress one gradient; accumulates error into the key's
        residual (reference Quantize, gradient_compression.h:103).
        `grad` is a raw jax array; returns packed uint32 words."""
        flat = grad.reshape(-1).astype(jnp.float32)
        size = flat.shape[0]
        res = self._residuals.get(key)
        if res is None or res.shape != flat.shape:
            res = jnp.zeros_like(flat)
        words, new_res = _quantize_2bit(flat, res,
                                        jnp.float32(self.threshold), size)
        self._residuals[key] = new_res
        return words

    def dequantize(self, words, shape, dtype=jnp.float32):
        """Reference Dequantize: expand packed words back to values."""
        size = int(_np.prod(shape)) if shape else 1
        vals = _dequantize_2bit(words, jnp.float32(self.threshold), size)
        return vals.reshape(shape).astype(dtype)

    def dequantize_sum(self, stacked_words, shape, dtype=jnp.float32):
        """Decode a (n_workers, n_words) stack and sum over workers in ONE
        fused XLA program — the dist-store reduce of all workers'
        compressed gradients (kvstore_dist.h compressed merge) without a
        per-worker kernel launch."""
        size = int(_np.prod(shape)) if shape else 1
        vals = _dequantize_2bit(stacked_words.reshape(-1),
                                jnp.float32(self.threshold),
                                int(stacked_words.shape[0]) *
                                int(stacked_words.shape[1]) * _BLOCK)
        per_worker = vals.reshape(stacked_words.shape[0], -1)[:, :size]
        return per_worker.sum(axis=0).reshape(shape).astype(dtype)
