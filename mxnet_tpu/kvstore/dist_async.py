"""``dist_async`` — asynchronous parameter-server KVStore.

Reference: ``src/kvstore/kvstore_dist_server.h:325-349`` — in async mode
``DataHandleDefault`` applies each worker's push to the server weights
IMMEDIATELY (no merge buffer, no wait-for-all-workers barrier); workers
pull whatever the server holds at that instant, so gradient staleness is
allowed in exchange for never blocking on stragglers. Factory string:
``src/kvstore/kvstore.cc:42-85`` (``dist_async``).

TPU-native design: synchronous training is XLA collectives
(``dist_tpu_sync``) — but async-by-design has NO collective analog
(collectives are barriers by construction). So this keeps the
reference's topology: a host-side server thread on rank 0 owning the
store + updater, plain TCP from every worker. The device never blocks —
pushes ship host copies, and the optimizer runs on the server exactly
like ``update_on_kvstore`` on the reference PS. Semantics > transport
speed here (the VERDICT r1 item 4 contract); the synchronous fast path
remains dist_tpu_sync's fused collectives.

Wire format: JSON (cmd, key, dtype, shape) header + raw bytes — JSON,
not pickle, so a reachable port cannot execute code via a crafted
header.  The one pickled payload (``set_optimizer``) is gated behind a
shared-secret token (``MXNET_KVSTORE_SECRET``); without a configured
secret it is only accepted from loopback peers.  The server binds the
coordinator interface from ``MX_COORDINATOR`` rather than 0.0.0.0.
Server address: rank 0's host from ``MX_COORDINATOR`` with port offset
``MXNET_KVSTORE_ASYNC_PORT`` (default coordinator port + 29).
"""

import json
import os
import pickle
import socket
import socketserver
import struct
import threading

import numpy as _onp

from ..ndarray.ndarray import NDArray
from .base import KVStoreBase, register


def _recv_exact(sock, n):
    buf = b''
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError('kvstore async peer closed')
        buf += chunk
    return buf


def _send_msg(sock, header, payload=b''):
    head = json.dumps(header).encode('utf-8')
    sock.sendall(struct.pack('!II', len(head), len(payload)))
    sock.sendall(head)
    if payload:
        sock.sendall(payload)


def _recv_msg(sock):
    hlen, plen = struct.unpack('!II', _recv_exact(sock, 8))
    header = json.loads(_recv_exact(sock, hlen).decode('utf-8'))
    payload = _recv_exact(sock, plen) if plen else b''
    return header, payload


class _AsyncServer(threading.Thread):
    """The PS: one instance on rank 0 (reference KVStoreDistServer::Run).
    Every request handler applies immediately under the store lock —
    the async branch of DataHandleDefault."""

    def __init__(self, port, bind_host='127.0.0.1'):
        super().__init__(daemon=True)
        self._store = {}
        self._updater = None
        self._lock = threading.Lock()
        self._secret = os.environ.get('MXNET_KVSTORE_SECRET', '')
        # addresses that count as "same host" for the no-secret
        # set_optimizer gate: loopback plus the bind interface itself
        # (rank 0 dialing hostA:port arrives with hostA's own source IP)
        self._local_peers = {'127.0.0.1', '::1'}
        try:
            self._local_peers.add(socket.gethostbyname(bind_host))
        except OSError:
            pass
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        header, payload = _recv_msg(self.request)
                    except (ConnectionError, OSError, ValueError):
                        return
                    reply, rpayload = outer._dispatch(
                        header, payload, self.client_address[0])
                    _send_msg(self.request, reply, rpayload)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        # bind the coordinator interface (not 0.0.0.0): workers reach us
        # at this address anyway, and nothing else should
        try:
            self._server = Server((bind_host, port), Handler)
        except OSError:
            # coordinator hostname may not be a local interface name
            # (NAT/containers): fall back to all interfaces like ps-lite
            self._server = Server(('0.0.0.0', port), Handler)

    def run(self):
        self._server.serve_forever(poll_interval=0.05)

    def stop(self):
        self._server.shutdown()

    # ----------------------------------------------------------- handlers
    def _dispatch(self, header, payload, peer='127.0.0.1'):
        cmd = header['cmd']
        if cmd == 'init':
            arr = _onp.frombuffer(payload, header['dtype']).reshape(
                header['shape']).copy()
            with self._lock:
                # first init wins (reference: rank 0 authoritative)
                self._store.setdefault(header['key'], arr)
            return {'ok': True}, b''
        if cmd == 'push':
            grad = _onp.frombuffer(payload, header['dtype']).reshape(
                header['shape'])
            with self._lock:
                w = self._store.get(header['key'])
                if w is None:
                    self._store[header['key']] = grad.copy()
                elif self._updater is not None:
                    # immediate apply — the async DataHandleDefault branch
                    wn = NDArray(w)
                    self._updater(header['key'], NDArray(grad), wn)
                    self._store[header['key']] = _onp.asarray(
                        wn.asnumpy())
                else:
                    self._store[header['key']] = w + grad
            return {'ok': True}, b''
        if cmd == 'pull':
            with self._lock:
                w = self._store[header['key']]
                data = _onp.ascontiguousarray(w)
            return {'ok': True, 'dtype': str(data.dtype),
                    'shape': data.shape}, data.tobytes()
        if cmd == 'set_optimizer':
            # the only pickled payload on the wire: gate it.  With a
            # configured shared secret, require the token; without one,
            # only trust loopback peers (same-host job).
            import hmac
            if self._secret:
                if not hmac.compare_digest(header.get('token', ''),
                                           self._secret):
                    return {'ok': False,
                            'error': 'set_optimizer rejected: bad or '
                                     'missing MXNET_KVSTORE_SECRET '
                                     'token'}, b''
            elif not peer.startswith('127.') \
                    and peer not in self._local_peers:
                return {'ok': False,
                        'error': 'set_optimizer rejected from non-'
                                 'local peer: set '
                                 'MXNET_KVSTORE_SECRET on all ranks '
                                 'to enable remote optimizer setup'}, b''
            from ..optimizer import get_updater
            opt = pickle.loads(payload)
            with self._lock:
                self._updater = get_updater(opt)
            return {'ok': True}, b''
        if cmd == 'barrier':
            n = header['nproc']
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= n:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    released = self._barrier_cv.wait_for(
                        lambda: self._barrier_gen != gen, timeout=120)
                    if not released:
                        # undo our arrival so later barriers don't
                        # release one worker early, and surface the
                        # failure to the caller instead of silently
                        # proceeding unsynchronized
                        self._barrier_count -= 1
                        return {'ok': False,
                                'error': 'barrier timeout after 120s: '
                                         'not all workers arrived'}, b''
            return {'ok': True}, b''
        return {'ok': False, 'error': f'unknown cmd {cmd!r}'}, b''


_SERVERS = {}


@register
class KVStoreDistAsync(KVStoreBase):
    """Asynchronous PS kvstore (reference ``dist_async``)."""

    NAME = 'dist_async'

    def __init__(self):
        self._rank = int(os.environ.get('MX_PROC_ID', '0'))
        self._nproc = int(os.environ.get('MX_NPROC', '1'))
        self._sock = None
        self._server = None
        self._port = None
        self._host = ' '

    # ------------------------------------------------------------ plumbing
    def _ensure_connected(self):
        if self._sock is not None:
            return
        coord = os.environ.get('MX_COORDINATOR', '127.0.0.1:49800')
        host, port = coord.rsplit(':', 1)
        self._port = int(os.environ.get('MXNET_KVSTORE_ASYNC_PORT',
                                        int(port) + 29))
        self._host = host
        if self._rank == 0 and self._server is None:
            # one server per process regardless of how many dist_async
            # stores the worker creates (the reference's server process
            # is likewise shared across kvstore handles)
            self._server = _SERVERS.get(self._port)
            if self._server is None:
                bind = '127.0.0.1' if host in ('127.0.0.1',
                                               'localhost') else host
                self._server = _AsyncServer(self._port, bind_host=bind)
                self._server.start()
                _SERVERS[self._port] = self._server
        # every rank (rank 0 included) connects to the advertised
        # coordinator host: the server may be bound to that interface
        # only, so rank 0 dialing loopback would be refused
        target = '127.0.0.1' if host in ('127.0.0.1', 'localhost') \
            else host
        last = None
        for _ in range(100):
            try:
                self._sock = socket.create_connection(
                    (target, self._port), timeout=5)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
                return
            except OSError as e:
                last = e
                import time
                time.sleep(0.1)
        raise ConnectionError(
            f'cannot reach dist_async server at {target}:{self._port}: '
            f'{last}')

    def _rpc(self, header, payload=b''):
        self._ensure_connected()
        _send_msg(self._sock, header, payload)
        reply, rpayload = _recv_msg(self._sock)
        if not reply.get('ok'):
            raise RuntimeError(reply.get('error', 'kvstore rpc failed'))
        return reply, rpayload

    @staticmethod
    def _to_host(v):
        a = v.asnumpy() if isinstance(v, NDArray) else _onp.asarray(v)
        a = _onp.ascontiguousarray(a)
        return a

    # ------------------------------------------------------------- surface
    def init(self, key, value):
        keys = key if isinstance(key, (list, tuple)) else [key]
        vals = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, vals):
            a = self._to_host(v)
            self._rpc({'cmd': 'init', 'key': k, 'dtype': str(a.dtype),
                       'shape': a.shape}, a.tobytes())

    def push(self, key, value, priority=0):
        keys = key if isinstance(key, (list, tuple)) else [key]
        vals = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):   # local replicas: sum first
                import jax.numpy as jnp
                v = NDArray(jnp.sum(jnp.stack([x._data for x in v]), 0))
            a = self._to_host(v)
            # no merge buffer, no worker barrier: the server applies this
            # push before replying (async semantics)
            self._rpc({'cmd': 'push', 'key': k, 'dtype': str(a.dtype),
                       'shape': a.shape}, a.tobytes())

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = key if isinstance(key, (list, tuple)) else [key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        import jax.numpy as jnp
        results = []
        for k, o in zip(keys, outs):
            reply, payload = self._rpc({'cmd': 'pull', 'key': k})
            arr = _onp.frombuffer(payload, reply['dtype']).reshape(
                reply['shape'])
            raw = jnp.asarray(arr)
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                if t is not None:
                    t._rebind(raw)
            results.append(NDArray(raw))
        return results if isinstance(key, (list, tuple)) else results[0]

    def pushpull(self, key, value, out=None, priority=0):
        """Async pushpull = push, then pull whatever the server holds —
        other workers' concurrent pushes may or may not be included
        (exactly the reference's dist_async staleness contract)."""
        self.push(key, value, priority)
        self.pull(key, out=out if out is not None else value,
                  priority=priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.barrier()
        self.pull(key, out=out, priority=priority)

    def set_optimizer(self, optimizer):
        """Pickle the optimizer to the server (reference
        _send_command_to_servers + kSetMultiPrecision path).  Only rank
        0 actually sends it — the reference likewise issues the server
        command from rank 0 alone, and the Trainer calls this on every
        rank.  Ordering is safe: workers cannot push before the
        broadcast barrier in ``_init_params``, which rank 0 only
        reaches after this RPC completes.  The request carries the
        shared-secret token so the server will unpickle it (see module
        docstring)."""
        if self._rank != 0:
            return
        self._rpc({'cmd': 'set_optimizer',
                   'token': os.environ.get('MXNET_KVSTORE_SECRET', '')},
                  pickle.dumps(optimizer))

    def set_updater(self, updater):
        raise NotImplementedError(
            'dist_async runs the updater on the server; use '
            'set_optimizer (reference kvstore_dist.h same restriction)')

    def set_gradient_compression(self, compression_params):
        raise ValueError('gradient compression is not supported on '
                         'dist_async (reference supports it on the sync '
                         'PS path only)')

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._nproc

    def barrier(self):
        """Explicit rendezvous (reference ps::Postoffice::Barrier) —
        NOT implied by push/pull, which never wait for other workers."""
        self._rpc({'cmd': 'barrier', 'nproc': self._nproc})

    def get_num_dead_node(self, node_id=0, timeout=60):
        return 0

    @property
    def type(self):
        return 'dist_async'

    @staticmethod
    def is_capable(capability):
        return capability.lower() in ('optimizer', 'init')
