"""``dist_async`` — asynchronous parameter-server KVStore.

Reference: ``src/kvstore/kvstore_dist_server.h:325-349`` — in async mode
``DataHandleDefault`` applies each worker's push to the server weights
IMMEDIATELY (no merge buffer, no wait-for-all-workers barrier); workers
pull whatever the server holds at that instant, so gradient staleness is
allowed in exchange for never blocking on stragglers. Factory string:
``src/kvstore/kvstore.cc:42-85`` (``dist_async``).

TPU-native design: synchronous training is XLA collectives
(``dist_tpu_sync``) — but async-by-design has NO collective analog
(collectives are barriers by construction). So this keeps the
reference's topology: a host-side server thread on rank 0 owning the
store + updater, plain TCP from every worker. The device never blocks —
pushes ship host copies, and the optimizer runs on the server exactly
like ``update_on_kvstore`` on the reference PS. Semantics > transport
speed here (the VERDICT r1 item 4 contract); the synchronous fast path
remains dist_tpu_sync's fused collectives.

Wire format: JSON (cmd, key, dtype, shape) header + raw bytes — JSON,
not pickle, so a reachable port cannot execute code via a crafted
header.  The one pickled payload (``set_optimizer``) is gated behind a
shared-secret token (``MXNET_KVSTORE_SECRET``); without a configured
secret it is only accepted from loopback peers.  Server 0 binds the
coordinator interface from ``MX_COORDINATOR`` rather than 0.0.0.0;
servers sid>0 bind the interface their host reaches server 0 through
(the same address they advertise) — no server listens on every NIC.
Server address: rank 0's host from ``MX_COORDINATOR`` with port offset
``MXNET_KVSTORE_ASYNC_PORT`` (default coordinator port + 29).

The transport layer (framing, handler loop, heartbeat table,
tombstones, (client, seq) dedup window, retrying client channel) lives
in :mod:`mxnet_tpu.kvstore.rpc` — ``_AsyncServer`` subclasses
:class:`~mxnet_tpu.kvstore.rpc.RpcServer` and registers the kvstore
command set; the replicated serving tier (``mxnet_tpu/serve/router.py``)
registers its own handlers on the same machinery.

Capacity (reference ``kvstore_dist.h:621`` EncodeDefaultKey):

* **Multi-server key sharding** — ``MXNET_KVSTORE_NUM_SERVERS=S``
  starts one server thread on each of ranks 0..S-1 (server s at port
  base+s); servers s>0 register their reachable address with server 0,
  and every worker learns the table from there. Keys are routed by
  CRC32(key) % S, so load and optimizer compute spread across servers.
* **Big-array splitting** — arrays of at least
  ``MXNET_KVSTORE_BIGARRAY_BOUND`` bytes (default 1 MB, the reference
  default) with enough rows are split into S contiguous row ranges,
  chunk k living on server k — one huge embedding table does not pin a
  single server (reference bigarray_bound_ slicing).
* **Failure detection** — every worker runs a heartbeat thread pinging
  server 0 (``MXNET_KVSTORE_HEARTBEAT_S``, default 2s);
  ``get_num_dead_node(timeout=t)`` reports workers whose last beat is
  older than ``t`` plus any unreachable server — a real answer, not
  the stub the reference's Postoffice heartbeat would give
  (ps-lite Postoffice::GetDeadNodes).
* **Fault tolerance** (docs/fault-tolerance.md) — every RPC retries
  with exponential backoff + jitter under a per-call deadline and
  redials broken sockets (``MXNET_KVSTORE_RPC_RETRIES`` /
  ``MXNET_KVSTORE_RPC_DEADLINE_S`` / ``MXNET_KVSTORE_RPC_BACKOFF_S``),
  so a server restart or TCP reset is absorbed, not fatal (≙ ps-lite
  Resender). Mutating RPCs carry a per-store ``(client, seq)`` identity
  deduped in a server-side replay window
  (``MXNET_KVSTORE_DEDUP_WINDOW``): a retried already-applied push is
  answered from cache — exactly-once gradients under retry. Ranks that
  send ``bye`` are tombstoned so a delayed in-flight ping cannot
  resurrect them in the dead-node accounting. Every recovery path is
  testable in-process through the deterministic fault-injection hooks
  in ``mxnet_tpu/kvstore/faults.py``
  (``MXNET_KVSTORE_FAULT_SPEC``).
"""

import os
import pickle
import socket
import threading

import numpy as _onp

from ..ndarray.ndarray import NDArray
from ..telemetry import trace as _trace
from . import faults
from .base import KVStoreBase, register
# framing helpers re-exported from their historical home: faults-harness
# docs and older callers name them as dist_async._send_msg etc.
from .rpc import (RpcClient, RpcServer, _recv_exact,  # noqa: F401
                  _recv_msg, _send_msg)

# RPCs that change server state: they carry a per-store (client, seq)
# identity so a retry of an applied-but-reply-lost request is answered
# from the server's dedup window instead of re-applied (pull/ping/stats
# are idempotent and need no window)
_MUTATING_CMDS = frozenset(
    {'init', 'push', 'set_optimizer', 'register_server', 'barrier',
     'put', 'elastic_join', 'elastic_leave', 'elastic_commit',
     'elastic_barrier', 'mesh_join', 'mesh_leave', 'mesh_epoch'})

# data-plane commands stamped with the client's cached mesh generation
# (once set_mesh_gen/mesh_join ran): the server's generation fence
# rejects them typed after a re-formation instead of silently applying
# a stale world's update. Mesh verbs themselves are never stamped —
# they are how a client LEARNS the current generation.
_MESH_STAMPED_CMDS = frozenset({'init', 'push', 'pull', 'put'})


class _AsyncServer(RpcServer):
    """The PS: one instance on rank 0 (reference KVStoreDistServer::Run).
    Every request handler applies immediately under the store lock —
    the async branch of DataHandleDefault. Transport machinery
    (handler loop, heartbeat table, dedup window) comes from
    :class:`~mxnet_tpu.kvstore.rpc.RpcServer`."""

    LOCK_LEVEL = 'kvstore.store'
    # data-plane commands prove a live store: they lift a tombstone (a
    # NEW store of a departed rank revives it); ping/bye/queries do not
    _REVIVING_CMDS = frozenset(
        {'init', 'push', 'pull', 'barrier', 'set_optimizer', 'put',
         'elastic_join', 'elastic_barrier', 'elastic_commit',
         'mesh_join'})

    def __init__(self, port, bind_host='127.0.0.1', sid=0):
        super().__init__(port, bind_host=bind_host, sid=sid)
        self._store = {}
        self._updater = None
        self._server_table = {}     # sid -> 'host:port' (server 0 only)
        self._counters.update({'init_applied': 0, 'push_applied': 0})
        self._secret = os.environ.get('MXNET_KVSTORE_SECRET', '')
        # addresses that count as "same host" for the no-secret
        # set_optimizer gate: loopback plus the bind interface itself
        # (rank 0 dialing hostA:port arrives with hostA's own source IP)
        self._local_peers = {'127.0.0.1', '::1'}
        try:
            self._local_peers.add(socket.gethostbyname(bind_host))
        except OSError:
            pass
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_arrivals = set()   # (client, seq) this generation
        self._barrier_cv = threading.Condition()
        # ------- elastic membership (train.elastic worker-loss recovery)
        # rank -> {'joined': clock time, 'start': first step this member
        # participates in} — a late joiner must not be counted at a
        # barrier for a step already in flight (its gradient would be
        # scaled for a world it was never part of)
        self._elastic_members = {}
        self._elastic_gen = 0            # bumps on every join/ejection
        self._elastic_committed = -1     # last checkpoint-committed step
        self._elastic_step = -1          # max step whose barrier released
        self._elastic_arrivals = {}      # (phase, step) -> set of ranks
        self._elastic_rel = {}           # (phase, step) -> release count
        self._elastic_reply = {}         # (phase, step) -> last release reply
        self._elastic_cv = threading.Condition()
        self._race = None
        from ..analysis import race as _race
        if _race.enabled():
            # self._lock is already tracked at 'kvstore.store' by the
            # RpcServer base; every _store mutation must hold it —
            # handler threads race each other and the heartbeat reaper
            self._barrier_cv = _race.tracked_condition(
                self._barrier_cv, 'kvstore.barrier')
            self._elastic_cv = _race.tracked_condition(
                self._elastic_cv, 'kvstore.barrier')
            self._race = _race.shared_state('kvstore._AsyncServer._store',
                                            guard=self._lock)

    # ----------------------------------------------------------- handlers
    def _handle_app(self, header, payload, peer='127.0.0.1'):
        cmd = header['cmd']
        rank = header.get('rank')
        if cmd == 'register_server':
            with self._lock:
                self._server_table[int(header['sid'])] = header['addr']
            return {'ok': True}, b''
        if cmd == 'server_table':
            with self._lock:
                return {'ok': True,
                        'table': {str(k): v for k, v
                                  in self._server_table.items()}}, b''
        if cmd == 'stats':
            with self._lock:
                reply = {'ok': True, 'sid': self._sid,
                         'keys': sorted(map(str, self._store)),
                         'counters': dict(self._counters),
                         'tombstones': sorted(self._tombstones),
                         'faults': faults.injected()}
            with self._elastic_cv:
                reply['elastic'] = {
                    'gen': self._elastic_gen,
                    'live': sorted(self._elastic_members),
                    'committed': self._elastic_committed,
                    'step': self._elastic_step}
            with self._lock:
                reply['mesh'] = {'gen': self._mesh_gen,
                                 'members': sorted(self._mesh_members)}
            return reply, b''
        if cmd == 'init':
            arr = _onp.frombuffer(payload, header['dtype']).reshape(
                header['shape']).copy()
            with self._lock:
                if self._race is not None:
                    self._race.write()
                # first init wins (reference: rank 0 authoritative)
                self._store.setdefault(header['key'], arr)
                self._counters['init_applied'] += 1
            return {'ok': True}, b''
        if cmd == 'put':
            # unconditional overwrite — the rollback/recovery primitive:
            # init's first-write-wins would keep the value being rolled
            # back, and push routes through the updater
            arr = _onp.frombuffer(payload, header['dtype']).reshape(
                header['shape']).copy()
            with self._lock:
                if self._race is not None:
                    self._race.write()
                self._store[header['key']] = arr
            return {'ok': True}, b''
        if cmd == 'push':
            grad = _onp.frombuffer(payload, header['dtype']).reshape(
                header['shape'])
            with self._lock:
                if self._race is not None:
                    self._race.write()
                w = self._store.get(header['key'])
                if w is None:
                    self._store[header['key']] = grad.copy()
                elif self._updater is not None:
                    # immediate apply — the async DataHandleDefault branch
                    wn = NDArray(w)
                    self._updater(header['key'], NDArray(grad), wn)
                    # the sync IS the apply: a pull must never observe a
                    # half-applied weight, so it stays under the store
                    # lock
                    self._store[header['key']] = _onp.asarray(
                        wn.asnumpy())  # lock-lint: disable=blocking-call-under-lock -- server-side updater runs on host CPU arrays; syncing outside the store lock would let pulls read torn updates
                else:
                    self._store[header['key']] = w + grad
                self._counters['push_applied'] += 1
            return {'ok': True}, b''
        if cmd == 'pull':
            with self._lock:
                if self._race is not None:
                    self._race.read()
                w = self._store.get(header['key'])
                if w is None:
                    # a clean error keeps the connection alive (a raise
                    # would kill this handler thread and drop the socket)
                    return {'ok': False,
                            'error': f'no such key {header["key"]!r} on '
                                     f'server {self._sid}'}, b''
                data = _onp.ascontiguousarray(w)
            return {'ok': True, 'dtype': str(data.dtype),
                    'shape': data.shape}, data.tobytes()
        if cmd == 'set_optimizer':
            # the only pickled payload on the wire: gate it.  With a
            # configured shared secret, require the token; without one,
            # only trust loopback peers (same-host job).
            import hmac
            if self._secret:
                if not hmac.compare_digest(header.get('token', ''),
                                           self._secret):
                    return {'ok': False,
                            'error': 'set_optimizer rejected: bad or '
                                     'missing MXNET_KVSTORE_SECRET '
                                     'token'}, b''
            elif not peer.startswith('127.') \
                    and peer not in self._local_peers:
                return {'ok': False,
                        'error': 'set_optimizer rejected from non-'
                                 'local peer: set '
                                 'MXNET_KVSTORE_SECRET on all ranks '
                                 'to enable remote optimizer setup'}, b''
            from ..optimizer import get_updater
            opt = pickle.loads(payload)
            with self._lock:
                self._updater = get_updater(opt)
            return {'ok': True}, b''
        if cmd == 'barrier':
            n = header['nproc']
            # retry identity: a worker whose connection died while its
            # original barrier handler is still blocked in wait_for
            # re-sends the SAME (client, seq) on a fresh socket — that
            # duplicate must wait for the release, not arrive twice
            ident = (header.get('client'), header.get('seq'))
            with self._barrier_cv:
                gen = self._barrier_gen
                if ident == (None, None) \
                        or ident not in self._barrier_arrivals:
                    self._barrier_arrivals.add(ident)
                    self._barrier_count += 1
                if self._barrier_count >= n:
                    self._barrier_count = 0
                    self._barrier_arrivals = set()
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    deadline = _kv_deadline_s()
                    released = self._barrier_cv.wait_for(
                        lambda: self._barrier_gen != gen,
                        timeout=deadline)
                    if not released:
                        # undo our arrival so later barriers don't
                        # release one worker early, and surface the
                        # failure to the caller instead of silently
                        # proceeding unsynchronized
                        self._barrier_count -= 1
                        self._barrier_arrivals.discard(ident)
                        return {'ok': False,
                                'error': f'barrier timeout after '
                                         f'{deadline:g}s '
                                         f'(MXNET_KVSTORE_DEADLINE_S): '
                                         f'not all workers arrived'}, b''
            return {'ok': True}, b''
        if cmd == 'elastic_join':
            r = int(rank)
            with self._elastic_cv:
                # a (re)joining worker participates from the first step
                # whose barrier has not released yet: the in-flight step
                # keeps the world it started with
                start = max(self._elastic_step,
                            self._elastic_committed) + 1
                if r not in self._elastic_members:
                    self._elastic_members[r] = {'joined': self._clock(),
                                                'start': start}
                    self._elastic_gen += 1
                    self._elastic_cv.notify_all()
                return {'ok': True, 'gen': self._elastic_gen,
                        'live': sorted(self._elastic_members),
                        'committed': self._elastic_committed,
                        'resume': self._elastic_members[r]['start']}, b''
        if cmd == 'elastic_leave':
            r = int(rank)
            with self._elastic_cv:
                if self._elastic_members.pop(r, None) is not None:
                    self._elastic_gen += 1
                    self._elastic_cv.notify_all()
                return {'ok': True, 'gen': self._elastic_gen,
                        'live': sorted(self._elastic_members)}, b''
        if cmd == 'elastic_commit':
            step = int(header['step'])
            with self._elastic_cv:
                self._elastic_committed = max(self._elastic_committed,
                                              step)
                # prune barrier bookkeeping for steps that can never be
                # revisited (rollback never goes behind the commit)
                for k in [k for k in self._elastic_arrivals
                          if k[1] < self._elastic_committed - 2]:
                    self._elastic_arrivals.pop(k, None)
                    self._elastic_rel.pop(k, None)
                    self._elastic_reply.pop(k, None)
                self._elastic_cv.notify_all()
                return {'ok': True,
                        'committed': self._elastic_committed}, b''
        if cmd == 'elastic_barrier':
            return self._elastic_barrier(header)
        return {'ok': False, 'error': f'unknown cmd {cmd!r}'}, b''

    def _elastic_barrier(self, header):
        """Membership-aware barrier for the elastic step protocol.

        Release condition: every *expected* member (live, and whose
        ``start`` step is <= this barrier's step) has arrived. While
        waiting, each waiter re-evaluates liveness from the heartbeat
        table against the injectable clock and EJECTS silent members —
        only non-arrived ones: an arrived member is a live handler
        thread by construction, no matter how stale its fake-clock
        heartbeat looks. Barriers are re-runnable: a release clears the
        arrivals set and caches the reply, so a rollback-redo of the
        same (phase, step) forms a fresh barrier instead of releasing
        instantly off stale arrivals.

        Lock order: the heartbeat snapshot is taken under ``self._lock``
        (kvstore.store) and RELEASED before ``_elastic_cv``
        (kvstore.barrier) is acquired — store before barrier, matching
        the declared hierarchy.
        """
        import time as _time
        rank = int(header['rank'])
        phase = header['phase']
        step = int(header['step'])
        key = (phase, step)
        deadline = _kv_deadline_s()
        wall_deadline = _time.monotonic() + deadline
        entry_gen = None
        entry_rel = None
        while True:
            with self._lock:
                seen = {r: t for r, t in self._last_seen.items()}
                tombs = set(self._tombstones)
            now = self._clock()
            with self._elastic_cv:
                if rank not in self._elastic_members:
                    return {'ok': False,
                            'error': f'rank {rank} is not an elastic '
                                     'member (call elastic_join '
                                     'first)'}, b''
                if entry_gen is None:
                    entry_gen = self._elastic_gen
                    entry_rel = self._elastic_rel.get(key, 0)
                elif self._elastic_rel.get(key, 0) > entry_rel:
                    # another waiter released this barrier round: join
                    # its verdict so the whole group acts uniformly.
                    # Checked BEFORE registering arrival — a woken
                    # waiter must not seed the next run of this
                    # (phase, step) barrier with its stale rank
                    return dict(self._elastic_reply[key]), b''
                arr = self._elastic_arrivals.setdefault(key, set())
                arr.add(rank)
                dead = []
                for r, m in self._elastic_members.items():
                    if r in arr:
                        continue
                    if r in tombs or \
                            now - seen.get(r, m['joined']) > deadline:
                        dead.append(r)
                for r in dead:
                    del self._elastic_members[r]
                if dead:
                    self._elastic_gen += 1
                    self._elastic_cv.notify_all()
                expected = {r for r, m in self._elastic_members.items()
                            if m['start'] <= step}
                if expected and expected <= arr:
                    self._elastic_step = max(self._elastic_step, step)
                    reply = {'ok': True, 'gen': self._elastic_gen,
                             'live': sorted(self._elastic_members),
                             'count': len(expected),
                             'committed': self._elastic_committed,
                             'changed': self._elastic_gen != entry_gen}
                    self._elastic_rel[key] = \
                        self._elastic_rel.get(key, 0) + 1
                    self._elastic_reply[key] = reply
                    self._elastic_arrivals[key] = set()
                    self._elastic_cv.notify_all()
                    return dict(reply), b''
                if _time.monotonic() >= wall_deadline:
                    arr.discard(rank)
                    return {'ok': False,
                            'error': f'elastic barrier ({phase}, {step}) '
                                     f'timeout after {deadline:g}s '
                                     '(MXNET_KVSTORE_DEADLINE_S)'}, b''
                # short slices, not one long wait: fake-clock liveness
                # (self._clock) can advance without any notify, and the
                # per-iteration re-snapshot is what turns that into a
                # deterministic ejection
                self._elastic_cv.wait(timeout=0.05)


_SERVERS = {}
# guards _SERVERS: two stores connecting concurrently in one process
# must not double-create (and double-bind) the per-port server
_SERVERS_LOCK = threading.Lock()


def _kv_deadline_s():
    """Liveness deadline for control-plane waits (barrier wait_for,
    heartbeat join): ``MXNET_KVSTORE_DEADLINE_S`` (default 120) — a dead
    peer can no longer hang a barrier forever. Distinct from
    ``MXNET_KVSTORE_RPC_DEADLINE_S``, the per-RPC transport budget."""
    try:
        return max(1e-3, float(os.environ.get(
            'MXNET_KVSTORE_DEADLINE_S', '120')))
    except ValueError:
        return 120.0


@register
class KVStoreDistAsync(KVStoreBase):
    """Asynchronous PS kvstore (reference ``dist_async``)."""

    NAME = 'dist_async'

    def __init__(self):
        self._rank = int(os.environ.get('MX_PROC_ID', '0'))
        self._nproc = int(os.environ.get('MX_NPROC', '1'))
        self._chans = {}            # sid -> RpcClient channel
        self._addrs = {}            # sid -> (host, port) diagnostics
        self._server = None
        self._port = None
        self._host = ' '
        self._closed = False
        self._nserv = min(max(1, int(os.environ.get(
            'MXNET_KVSTORE_NUM_SERVERS', '1'))), self._nproc)
        self._big = int(float(os.environ.get(
            'MXNET_KVSTORE_BIGARRAY_BOUND', str(1 << 20))))
        self._hb_thread = None
        # resilient-transport knobs: a transient server restart or TCP
        # reset is absorbed by redial + retry instead of killing the
        # job (≙ ps-lite Resender/PS_RESEND, Van reconnect)
        self._rpc_retries = int(os.environ.get(
            'MXNET_KVSTORE_RPC_RETRIES', '4'))
        self._rpc_deadline = float(os.environ.get(
            'MXNET_KVSTORE_RPC_DEADLINE_S', '60'))
        self._rpc_backoff = float(os.environ.get(
            'MXNET_KVSTORE_RPC_BACKOFF_S', '0.05'))
        # per-store identity + monotonic sequence for mutating RPCs:
        # the server's dedup window keys on (client, seq) so a retried
        # already-applied push replays its reply (exactly-once). The
        # client id disambiguates several stores of the same rank in
        # one process (each runs its own seq counter from 0).
        import uuid
        self._client = uuid.uuid4().hex
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._transport_stats = {'retries': 0, 'redials': 0,
                                 'giveups': 0}
        # cached mesh generation: None until this store joined the mesh
        # (or set_mesh_gen ran) — only then are data-plane RPCs stamped
        # and subject to the server's generation fence
        self._mesh_gen = None

    # ------------------------------------------------------------ plumbing
    def _channel(self, sid, host, port):
        """Create + eagerly connect the retrying channel to server
        ``sid`` (all channels share one transport-stats dict)."""
        chan = RpcClient(host, int(port), label=f'server {sid}',
                         what='dist_async', retries=self._rpc_retries,
                         deadline_s=self._rpc_deadline,
                         backoff_s=self._rpc_backoff,
                         stats=self._transport_stats)
        chan.connect()
        self._addrs[sid] = (host, int(port))
        self._chans[sid] = chan
        return chan

    def _ensure_connected(self):
        if self._chans:
            return
        coord = os.environ.get('MX_COORDINATOR', '127.0.0.1:49800')
        host, port = coord.rsplit(':', 1)
        self._port = int(os.environ.get('MXNET_KVSTORE_ASYNC_PORT',
                                        int(port) + 29))
        self._host = host
        self._closed = False
        local = host in ('127.0.0.1', 'localhost')
        if self._rank == 0 and self._server is None:
            # rank 0 hosts server 0 (reference: the server node group;
            # one server per process regardless of how many dist_async
            # stores the worker creates) and must start it before
            # dialing itself below
            with _SERVERS_LOCK:
                self._server = _SERVERS.get(self._port)
                if self._server is None:
                    bind = '127.0.0.1' if local else host
                    self._server = _AsyncServer(self._port,
                                                bind_host=bind, sid=0)
                    self._server.start()
                    _SERVERS[self._port] = self._server
        # every rank (rank 0 included) connects to the advertised
        # coordinator host: the server may be bound to that interface
        # only, so rank 0 dialing loopback would be refused
        target = '127.0.0.1' if local else host
        self._channel(0, target, self._port)
        if self._nserv > 1:
            # server sid>0 starts only AFTER dialing server 0 and binds
            # the exact interface that dial used (getsockname) — the
            # same address it advertises in register_server. Binding
            # 0.0.0.0 here would expose the unauthenticated
            # init/push/pull data plane on every NIC (ADVICE r4).
            if 0 < self._rank < self._nserv:
                my_port = self._port + self._rank
                myif = self._chans[0].sock().getsockname()[0]
                with _SERVERS_LOCK:
                    self._server = _SERVERS.get(my_port)
                    if self._server is None:
                        self._server = _AsyncServer(
                            my_port,
                            bind_host='127.0.0.1' if local else myif,
                            sid=self._rank)
                        self._server.start()
                        _SERVERS[my_port] = self._server
                myaddr = f'{myif}:{my_port}'
                self._rpc_to(0, {'cmd': 'register_server',
                                 'sid': self._rank, 'addr': myaddr})
            table = {}
            import time
            for _ in range(200):
                reply, _p = self._rpc_to(0, {'cmd': 'server_table'})
                table = reply['table']
                if len(table) >= self._nserv - 1:
                    break
                time.sleep(0.1)
            else:
                raise ConnectionError(
                    f'only {len(table) + 1}/{self._nserv} dist_async '
                    'servers registered')
            for sid_s, addr in table.items():
                h, p = addr.rsplit(':', 1)
                self._channel(int(sid_s), h, int(p))
        if self._hb_thread is None:
            interval = float(os.environ.get('MXNET_KVSTORE_HEARTBEAT_S',
                                            '2'))
            self._hb_stop = threading.Event()
            # weakref: a strong self in the closure would keep the store
            # alive forever (thread references closure references store),
            # so __del__->close could never run for abandoned stores
            import weakref
            wself = weakref.ref(self)
            stop = self._hb_stop

            def beat():
                while not stop.wait(interval):
                    st = wself()
                    if st is None:
                        return        # store collected
                    try:
                        # single attempt, short deadline: a lost beat
                        # is harmless (the next one retries, and every
                        # real RPC piggybacks a heartbeat) — retrying
                        # here would pin the socket lock for seconds
                        st._rpc_to(0, {'cmd': 'ping'}, attempts=1,
                                   deadline_s=5)
                    except Exception:
                        return        # job shutting down
                    del st

            self._hb_thread = threading.Thread(target=beat, daemon=True)
            self._hb_thread.start()

    def close(self):
        """Stop the heartbeat thread and close this store's server
        connections (the server threads themselves are shared per-port
        and stay up for other stores in the process).

        Idempotent and shutdown-safe: a second call (or a __del__ at
        interpreter teardown racing an already-dead heartbeat thread,
        or one that runs before _ensure_connected ever did) returns
        without raising — router+replica teardown tears down many
        stores at GC time and none of them may throw."""
        if getattr(self, '_closed', False):
            return
        self._closed = True
        hb = getattr(self, '_hb_thread', None)
        if hb is not None:
            try:
                self._hb_stop.set()
                # join BEFORE the bye RPC: an in-flight ping landing
                # after the bye would re-add this rank to the server's
                # last-seen table and resurrect the dead-forever
                # accounting bug. Deadline-bounded: a pinger stuck in a
                # dying RPC must not hang close() (the thread is a
                # daemon; leaking it past the deadline is safe). An
                # already-dead thread joins immediately.
                hb.join(timeout=min(10.0, _kv_deadline_s()))
            except Exception:
                pass              # interpreter shutting down mid-close
            self._hb_thread = None
        chans = getattr(self, '_chans', None)
        if not chans:
            return
        if 0 in chans:
            try:
                # clean departure: deregister from the heartbeat table
                # so this rank is not counted dead forever (ADVICE r4);
                # single short attempt — shutdown must not hang on a
                # server that is already gone
                self._rpc_to(0, {'cmd': 'bye'}, attempts=1, deadline_s=5)
            except Exception:
                pass              # server already gone: nothing to tell
        for chan in list(chans.values()):
            try:
                chan.close()
            except Exception:
                pass
        chans.clear()
        self._addrs.clear()

    def __del__(self):                  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def _rpc_to(self, sid, header, payload=b'', attempts=None,
                deadline_s=None):
        """One RPC with retry/backoff + reconnect (the channel's
        :meth:`~mxnet_tpu.kvstore.rpc.RpcClient.call` contract).

        This wrapper owns identity: it stamps ``rank`` plus, for
        mutating RPCs, the per-store ``(client, seq)`` — exactly once,
        so the identity survives the channel's resends and the server
        dedup window sees a stable key. Application-level errors
        (``ok: False`` replies) are NOT retried — they surface as
        ``RuntimeError`` exactly as before."""
        header['rank'] = self._rank
        if header['cmd'] in _MUTATING_CMDS and 'seq' not in header:
            with self._seq_lock:
                self._seq += 1
                header['seq'] = self._seq
            header['client'] = self._client
        if self._mesh_gen is not None and 'gen' not in header \
                and header['cmd'] in _MESH_STAMPED_CMDS:
            header['gen'] = self._mesh_gen
        try:
            return self._chans[sid].call(header, payload,
                                         attempts=attempts,
                                         deadline_s=deadline_s)
        except RuntimeError as e:
            reply = getattr(e, 'reply', None) or {}
            if reply.get('kind') == 'StaleGeneration':
                from .rpc import StaleGeneration
                err = StaleGeneration(str(e))
                err.reply = reply
                raise err from None
            raise

    def _rpc(self, header, payload=b''):
        self._ensure_connected()
        return self._rpc_to(0, header, payload)

    # ------------------------------------------------------------- routing
    def _key_server(self, key):
        import zlib
        return zlib.crc32(str(key).encode()) % self._nserv

    def _plan(self, key, shape, nbytes):
        """Reference EncodeDefaultKey (kvstore_dist.h:621): small keys
        hash to one server; arrays >= bigarray_bound with enough rows
        split into contiguous row ranges, chunk k on server k. Every
        worker computes the identical plan from (key, shape)."""
        self._ensure_connected()
        if self._nserv == 1:
            return [(0, key, None)]
        if nbytes >= self._big and len(shape) >= 1 \
                and shape[0] >= self._nserv:
            rows, S = shape[0], self._nserv
            return [(k, f'{key}#c{k}',
                     (rows * k // S, rows * (k + 1) // S))
                    for k in range(S)]
        return [(self._key_server(key), key, None)]

    @staticmethod
    def _to_host(v):
        a = v.asnumpy() if isinstance(v, NDArray) else _onp.asarray(v)
        a = _onp.ascontiguousarray(a)
        return a

    # ------------------------------------------------------------- surface
    def init(self, key, value):
        keys = key if isinstance(key, (list, tuple)) else [key]
        vals = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, vals):
            a = self._to_host(v)
            for sid, sub, rng in self._plan(k, a.shape, a.nbytes):
                part = a if rng is None else a[rng[0]:rng[1]]
                self._rpc_to(sid, {'cmd': 'init', 'key': sub,
                                   'dtype': str(part.dtype),
                                   'shape': part.shape}, part.tobytes())

    def push(self, key, value, priority=0):
        # child-only span: a traced caller (telemetry.span around the
        # training step) sees its push/pull legs — and, through the tc
        # injected on each RPC, the server-side apply — as one trace;
        # untraced callers pay one context check
        with _trace.child_span('kvstore.push'):
            self._push(key, value, priority)

    def _push(self, key, value, priority=0):
        keys = key if isinstance(key, (list, tuple)) else [key]
        vals = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):   # local replicas: sum first
                import jax.numpy as jnp
                v = NDArray(jnp.sum(jnp.stack([x._data for x in v]), 0))
            a = self._to_host(v)
            # no merge buffer, no worker barrier: the server applies this
            # push before replying (async semantics)
            for sid, sub, rng in self._plan(k, a.shape, a.nbytes):
                part = a if rng is None else \
                    _onp.ascontiguousarray(a[rng[0]:rng[1]])
                self._rpc_to(sid, {'cmd': 'push', 'key': sub,
                                   'dtype': str(part.dtype),
                                   'shape': part.shape}, part.tobytes())

    def _pull_one(self, sid, sub):
        reply, payload = self._rpc_to(sid, {'cmd': 'pull', 'key': sub})
        return _onp.frombuffer(payload, reply['dtype']).reshape(
            reply['shape'])

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        with _trace.child_span('kvstore.pull'):
            return self._pull(key, out, priority, ignore_sparse)

    def _pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = key if isinstance(key, (list, tuple)) else [key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        import jax.numpy as jnp
        results = []
        for k, o in zip(keys, outs):
            tpl = o[0] if isinstance(o, (list, tuple)) else o
            if tpl is not None:
                # split routing is decided from the out template's shape
                # (identical on every worker — same plan as init/push)
                shape = tuple(tpl.shape)
                nbytes = tpl.dtype.itemsize * max(
                    1, int(_onp.prod(shape)))
                plan = self._plan(k, shape, nbytes)
            else:
                plan = self._plan(k, (), 0)
            if len(plan) == 1:
                try:
                    arr = self._pull_one(plan[0][0], plan[0][1])
                except RuntimeError as e:
                    # no out template and the key was init'd as a split
                    # big array: the unsplit name doesn't exist — fetch
                    # the chunks (chunk c lives on server c by plan)
                    if 'no such key' not in str(e) or self._nserv == 1:
                        raise
                    arr = _onp.concatenate(
                        [self._pull_one(c, f'{k}#c{c}')
                         for c in range(self._nserv)], axis=0)
            else:
                try:
                    arr = _onp.concatenate(
                        [self._pull_one(sid, sub)
                         for sid, sub, _ in plan], axis=0)
                except RuntimeError as e:
                    # the out template's shape/dtype planned a split the
                    # pushed array never had (e.g. a wider template
                    # dtype crossing bigarray_bound): fall back to the
                    # unsplit key on its hash server, mirroring the
                    # single-plan fallback above (ADVICE r4)
                    if 'no such key' not in str(e):
                        raise
                    arr = self._pull_one(self._key_server(k), k)
            raw = jnp.asarray(arr)
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                if t is not None:
                    t._rebind(raw)
            results.append(NDArray(raw))
        return results if isinstance(key, (list, tuple)) else results[0]

    def pushpull(self, key, value, out=None, priority=0):
        """Async pushpull = push, then pull whatever the server holds —
        other workers' concurrent pushes may or may not be included
        (exactly the reference's dist_async staleness contract)."""
        self.push(key, value, priority)
        self.pull(key, out=out if out is not None else value,
                  priority=priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.barrier()
        self.pull(key, out=out, priority=priority)

    def set_optimizer(self, optimizer):
        """Pickle the optimizer to the server (reference
        _send_command_to_servers + kSetMultiPrecision path).  Only rank
        0 actually sends it — the reference likewise issues the server
        command from rank 0 alone, and the Trainer calls this on every
        rank.  Ordering is safe: workers cannot push before the
        broadcast barrier in ``_init_params``, which rank 0 only
        reaches after this RPC completes.  The request carries the
        shared-secret token so the server will unpickle it (see module
        docstring)."""
        if self._rank != 0:
            return
        self._ensure_connected()
        blob = pickle.dumps(optimizer)
        token = os.environ.get('MXNET_KVSTORE_SECRET', '')
        for sid in sorted(self._chans):
            # every server runs the updater for the keys/chunks it owns
            self._rpc_to(sid, {'cmd': 'set_optimizer', 'token': token},
                         blob)

    def set_updater(self, updater):
        raise NotImplementedError(
            'dist_async runs the updater on the server; use '
            'set_optimizer (reference kvstore_dist.h same restriction)')

    def set_gradient_compression(self, compression_params):
        raise ValueError('gradient compression is not supported on '
                         'dist_async (reference supports it on the sync '
                         'PS path only)')

    def server_stats(self):
        """Per-server key inventory {sid: [keys]} — diagnostics/tests
        for the sharded layout (split chunks appear as 'key#cN')."""
        self._ensure_connected()
        out = {}
        for sid in sorted(self._chans):
            reply, _ = self._rpc_to(sid, {'cmd': 'stats'})
            out[sid] = reply['keys']
        return out

    def server_health(self):
        """Full per-server ``stats`` reply {sid: {...}}: key inventory,
        apply/dedup counters, tombstoned ranks, and (when a fault plan
        is armed in the server's process) ``faults.injected()``
        counters — the assertion surface for the resilience tests and
        the ``--kvstore-soak`` bench mode."""
        self._ensure_connected()
        out = {}
        for sid in sorted(self._chans):
            reply, _ = self._rpc_to(sid, {'cmd': 'stats'})
            out[sid] = {k: v for k, v in reply.items() if k != 'ok'}
        return out

    def transport_stats(self):
        """Worker-side resilience counters: ``retries`` (resends after
        a transport failure), ``redials`` (socket reconnects),
        ``giveups`` (RPCs that exhausted retries/deadline)."""
        return dict(self._transport_stats)

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._nproc

    def barrier(self):
        """Explicit rendezvous (reference ps::Postoffice::Barrier) —
        NOT implied by push/pull, which never wait for other workers."""
        self._rpc({'cmd': 'barrier', 'nproc': self._nproc})

    # ------------------------------------------------- elastic membership
    def put(self, key, value):
        """Unconditionally overwrite ``key`` on its server(s) — the
        rollback/recovery primitive (``init`` is first-write-wins and
        would keep exactly the value being rolled back)."""
        keys = key if isinstance(key, (list, tuple)) else [key]
        vals = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, vals):
            a = self._to_host(v)
            for sid, sub, rng in self._plan(k, a.shape, a.nbytes):
                part = a if rng is None else \
                    _onp.ascontiguousarray(a[rng[0]:rng[1]])
                self._rpc_to(sid, {'cmd': 'put', 'key': sub,
                                   'dtype': str(part.dtype),
                                   'shape': part.shape}, part.tobytes())

    def elastic_join(self):
        """Enter (or re-enter after a restart) the elastic membership
        group on server 0. Returns the join reply: ``live`` ranks,
        membership ``gen``, last ``committed`` step and the ``resume``
        step this worker participates from (a late joiner sits out any
        in-flight step)."""
        reply, _ = self._rpc({'cmd': 'elastic_join'})
        return {k: v for k, v in reply.items() if k != 'ok'}

    def elastic_leave(self):
        """Cleanly exit the elastic group (planned scale-down)."""
        reply, _ = self._rpc({'cmd': 'elastic_leave'})
        return {k: v for k, v in reply.items() if k != 'ok'}

    def elastic_commit(self, step):
        """Record that the checkpoint for ``step`` is durably committed
        — the step the group re-forms at after a failure."""
        reply, _ = self._rpc({'cmd': 'elastic_commit', 'step': int(step)})
        return int(reply['committed'])

    def elastic_barrier(self, phase, step):
        """Membership-aware rendezvous of the live elastic members for
        ``(phase, step)``. Blocks until every live member expected at
        this step arrives — silently dead members are ejected from the
        group within ``MXNET_KVSTORE_DEADLINE_S`` instead of hanging
        the barrier. Returns the release verdict: ``count`` (the world
        size this step runs at), ``live``, ``gen``, ``committed`` and
        ``changed`` (membership changed since this barrier formed —
        the caller's cue to roll back to the committed step)."""
        self._ensure_connected()
        # the handler legitimately blocks up to the liveness deadline;
        # give the transport room on top of it so a full barrier wait is
        # not misread as a dead server
        budget = _kv_deadline_s() + max(5.0, self._rpc_deadline)
        reply, _ = self._rpc_to(0, {'cmd': 'elastic_barrier',
                                    'phase': str(phase),
                                    'step': int(step)},
                                deadline_s=budget)
        return {k: v for k, v in reply.items() if k != 'ok'}

    # --------------------------------------------------- mesh membership
    def set_mesh_gen(self, gen):
        """Adopt ``gen`` as this store's mesh generation: every
        subsequent data-plane RPC (init/push/pull/put) is stamped with
        it and the server's generation fence rejects it typed
        (:class:`~mxnet_tpu.kvstore.rpc.StaleGeneration`) once the mesh
        re-formed past it. ``None`` un-stamps (pre-mesh behaviour)."""
        self._mesh_gen = None if gen is None else int(gen)

    def mesh_join(self, meta=None):
        """Join the pod mesh on server 0 (bumps the generation) and
        adopt the new generation. ``meta`` rides along into the
        membership table — mesh config, address, device inventory."""
        header = {'cmd': 'mesh_join'}
        if meta:
            header['meta'] = dict(meta)
        reply, _ = self._rpc(header)
        self.set_mesh_gen(reply['gen'])
        return {k: v for k, v in reply.items() if k != 'ok'}

    def mesh_leave(self):
        """Cleanly exit the mesh (planned scale-down; bumps the
        generation when this rank was actually a member)."""
        reply, _ = self._rpc({'cmd': 'mesh_leave'})
        return {k: v for k, v in reply.items() if k != 'ok'}

    def mesh_epoch(self, eject=(), bump=False):
        """Leader-driven re-formation: eject dead ``ranks`` and bump
        the generation once (idempotent — re-ejecting an already-gone
        rank is a no-op unless ``bump`` forces it). Adopts the new
        generation locally and returns it with the surviving members."""
        reply, _ = self._rpc({'cmd': 'mesh_epoch',
                              'eject': [int(r) for r in eject],
                              'bump': bool(bump)})
        self.set_mesh_gen(reply['gen'])
        return {k: v for k, v in reply.items() if k != 'ok'}

    def mesh_table(self):
        """Current membership as piggybacked on a heartbeat: ``gen`` +
        ``members`` — the follower's way to learn a re-formation it
        did not drive."""
        reply, _ = self._rpc({'cmd': 'ping'})
        return reply.get('mesh', {'gen': 0, 'members': []})

    def get_num_dead_node(self, node_id=0, timeout=60):
        """A real failure-detection answer (reference ps-lite
        Postoffice::GetDeadNodes via scheduler heartbeats): unreachable
        servers are pinged NOW; workers count as dead when their
        heartbeat (piggybacked on every RPC + the dedicated ping
        thread) is older than ``timeout`` seconds in server 0's
        last-seen table."""
        self._ensure_connected()
        dead = 0
        for sid in sorted(self._chans):
            try:
                self._rpc_to(sid, {'cmd': 'ping'})
            except Exception:
                dead += 1
        try:
            reply, _ = self._rpc_to(0, {'cmd': 'dead_nodes',
                                        'timeout': timeout})
            dead += int(reply['dead'])
        except Exception:
            pass
        return dead

    @property
    def type(self):
        return 'dist_async'

    @staticmethod
    def is_capable(capability):
        return capability.lower() in ('optimizer', 'init')
