"""``mx.optimizer`` — optimization algorithms.

Reference: ``python/mxnet/optimizer/`` (base optimizer.py:29 + one file per
algorithm) backed by fused C++/CUDA kernels (src/operator/optimizer_op.cc).
TPU design: each update rule is a pure jitted function over (weight, grad,
state...); XLA fuses the whole rule into one kernel, which is exactly what
the reference's hand-fused `sgd_mom_update`-style kernels achieve. Scalar
hyperparameters (lr, wd) are traced arguments so step-varying schedules
don't trigger recompilation.
"""

import math

import jax
import jax.numpy as jnp

from ..base import register as _register_factory, registry_create
from ..ndarray.ndarray import NDArray


class Optimizer:
    """Base optimizer (reference optimizer/optimizer.py:29)."""

    opt_registry = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=None, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, aggregate_num=0, use_fused_step=True):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate if learning_rate is not None else 0.01
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = aggregate_num
        self.param_dict = param_dict or {}
        self.idx2name = param_idx2name or {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError(f'Cannot find optimizer {name}')

    # ------------------------------------------------------------------ state
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        return self.create_state(index, weight)

    # ------------------------------------------------------------------- meta
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning(
                'LRScheduler of the optimizer has already been defined. '
                'Note that set_learning_rate can mutate the value of the '
                'learning rate of the optimizer only when the LRScheduler '
                'of the optimizer is undefined.')   # reference optimizer.py
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    def _get_lr(self, index):
        lr = self.learning_rate
        param = self.param_dict.get(index)
        if param is not None:
            lr *= getattr(param, 'lr_mult', 1.0)
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        param = self.param_dict.get(index)
        if param is not None:
            wd *= getattr(param, 'wd_mult', 1.0)
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _prep(self, grad):
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    # ---------------------------------------------------------------- updates
    def update(self, index, weight, grad, state):
        """In-place weight update. Accepts single values or lists
        (reference optimizer.py:295 supports aggregate updates)."""
        if isinstance(weight, (list, tuple)):
            for i, w, g, s in zip(index, weight, grad, state):
                self._update_one(i, w, g, s)
        else:
            self._update_one(index, weight, grad, state)

    update_multi_precision = update

    #: class-level: optimizer always does row-wise updates on row_sparse
    #: grads (reference adagrad.py:125 — sparse grads take the fused
    #: sparse.adagrad_update path unconditionally)
    _sparse_rowwise = False

    def _update_one(self, index, weight, grad, state):
        from ..ndarray import sparse as _sp
        if isinstance(grad, _sp.RowSparseNDArray):
            if getattr(self, 'lazy_update', False) or self._sparse_rowwise:
                return self._update_one_lazy(index, weight, grad, state)
            grad = grad.todense()   # std_update: all rows, incl. wd decay
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        new_w, new_state = self.step(weight._data, grad._data, state, lr, wd,
                                     t)
        # update math may promote (e.g. f32 lr x bf16 weight); the stored
        # weight keeps its dtype (reference kernels write in-place in the
        # weight's dtype — a bf16-cast net must stay bf16 across steps)
        if new_w.dtype != weight._data.dtype:
            new_w = new_w.astype(weight._data.dtype)
        weight._rebind(new_w)
        self._write_state(state, new_state)

    def _update_one_lazy(self, index, weight, grad, state):
        """Row-wise update on the rows present in a row_sparse grad
        (reference sgd.py lazy_update / sparse.adagrad_update): absent
        rows see no weight decay, no momentum decay, no state change —
        the semantics that make large sparse embeddings trainable."""
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        rows = grad.indices._data.astype(jnp.int32)
        vals = grad.data._data
        if getattr(grad, '_may_have_duplicates', False):
            # gradient-born row_sparse: one entry per token occurrence.
            # Merge to unique rows with static shapes: jnp.unique with a
            # fixed size pads, padded slots are routed OUT OF BOUNDS so
            # their scatter writes drop (XLA scatter OOB semantics) —
            # no dynamic shapes, no densify.
            n = rows.shape[0]
            uniq, inv = jnp.unique(rows, return_inverse=True, size=n,
                                   fill_value=-1)
            vals = jnp.zeros((n,) + vals.shape[1:],
                             vals.dtype).at[inv.reshape(-1)].add(vals)
            valid = uniq >= 0
            rows = jnp.where(valid, uniq,
                             weight.shape[0]).astype(jnp.int32)

        def take(s):
            if isinstance(s, NDArray):
                return NDArray(s._data[jnp.clip(rows, 0,
                                                s.shape[0] - 1)],
                               ctx=s._ctx)
            if isinstance(s, (list, tuple)):
                return type(s)(take(x) for x in s)
            return s

        w_raw = weight._data
        w_rows = w_raw[jnp.clip(rows, 0, w_raw.shape[0] - 1)]
        new_w_rows, new_srows = self.step(w_rows, vals, take(state), lr,
                                          wd, t)
        # OOB rows (padding) are dropped by the scatter
        weight._rebind(w_raw.at[rows].set(
            new_w_rows, mode='drop', unique_indices=False))
        self._write_state_rows(state, new_srows, rows)

    def _write_state_rows(self, state, new_state, rows):
        # mode='drop': out-of-bounds rows are dedup padding (see
        # _update_one_lazy) and must not write anywhere
        if state is None:
            return
        if isinstance(state, NDArray):
            n = new_state[0] if isinstance(new_state, tuple) else new_state
            state._rebind(state._data.at[rows].set(n, mode='drop'))
        elif isinstance(state, (list, tuple)):
            for s, n in zip(state, new_state):
                if isinstance(s, NDArray):
                    s._rebind(s._data.at[rows].set(n, mode='drop'))

    def _write_state(self, state, new_state):
        if state is None:
            return
        if isinstance(state, NDArray):
            state._rebind(new_state if not isinstance(new_state, tuple)
                          else new_state[0])
        elif isinstance(state, (list, tuple)):
            for s, n in zip(state, new_state):
                if isinstance(s, NDArray):
                    s._rebind(n)

    def step(self, w, g, state, lr, wd, t):
        raise NotImplementedError

    def __repr__(self):
        return f'{type(self).__name__}(lr={self.lr})'


register = Optimizer.register
create = Optimizer.create_optimizer


def _zeros_like_nd(weight):
    return NDArray(jnp.zeros_like(weight._data), ctx=weight._ctx)


@register
class SGD(Optimizer):
    """SGD with momentum (reference optimizer/sgd.py:111; fused kernel
    src/operator/optimizer_op.cc sgd_mom_update)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lazy_update=False,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return _zeros_like_nd(weight)
        return None

    def step(self, w, g, state, lr, wd, t):
        if self.momentum == 0.0:
            g = self._prep(g) + wd * w
            return _sgd_step(w, g, lr), None
        # fused update op: one pallas_call on TPU (slots aliased in
        # place), line-identical XLA math elsewhere
        from ..ops.optimizer_ops import fused_sgd_mom_step
        return fused_sgd_mom_step(
            w, g, state._data, lr=lr, wd=wd, momentum=self.momentum,
            rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient)


@jax.jit
def _sgd_step(w, g, lr):
    return w - lr * g


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference optimizer/nag.py)."""

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g) + wd * w
        if self.momentum == 0.0:
            return w - lr * g, None
        mom = state._data
        new_mom = self.momentum * mom - lr * g
        return w + self.momentum * new_mom - lr * g, new_mom


@register
class Adam(Optimizer):
    """Reference optimizer/adam.py; fused kernel adam_update."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, correct_bias=True, lazy_update=False,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.correct_bias = correct_bias
        self.lazy_update = lazy_update   # reference adam.py:77

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def step(self, w, g, state, lr, wd, t):
        # fused update op: one pallas_call on TPU (slots aliased in
        # place), line-identical XLA math elsewhere
        from ..ops.optimizer_ops import fused_adam_step
        new_w, m, v = fused_adam_step(
            w, g, state[0]._data, state[1]._data, lr=lr, wd=wd, t=t,
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
            rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient,
            correct_bias=self.correct_bias)
        return new_w, (m, v)


@register
class AdamW(Adam):
    """Decoupled weight decay (reference contrib adamw op
    src/operator/contrib/adamw.cc)."""

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g)
        m, v = state[0]._data, state[1]._data
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        return w - lr * (mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w), \
            (m, v)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g) + wd * w
        m, u = state[0]._data, state[1]._data
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        lr_t = lr / (1 - self.beta1 ** t)
        return w - lr_t * m / (u + 1e-8), (m, u)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self._m_schedule = {}          # per-parameter product of momentum_t

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g) + wd * w
        momentum_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t1 = self.beta1 * (1 - 0.5 *
                                    0.96 ** ((t + 1) * self.schedule_decay))
        # per-parameter schedule product keyed by the state tuple identity:
        # one multiply per parameter step, not one per optimizer call
        key = id(state[0])
        m_schedule = self._m_schedule.get(key, 1.0) * momentum_t
        self._m_schedule[key] = m_schedule
        self.m_schedule = m_schedule   # kept for API compatibility
        m_schedule_next = m_schedule * momentum_t1
        m, v = state[0]._data, state[1]._data
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        g_prime = g / (1 - m_schedule)
        m_prime = m / (1 - m_schedule_next)
        v_prime = v / (1 - self.beta2 ** t)
        m_bar = (1 - momentum_t) * g_prime + momentum_t1 * m_prime
        return w - lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon), (m, v)


@register
class AdaGrad(Optimizer):
    _sparse_rowwise = True   # reference adagrad.py:125

    def __init__(self, learning_rate=0.01, epsilon=1e-7, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return _zeros_like_nd(weight)

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g) + wd * w
        h = state._data + g * g
        return w - lr * g / (jnp.sqrt(h) + self.epsilon), h


@register
class AdaDelta(Optimizer):
    def __init__(self, learning_rate=1.0, rho=0.90, epsilon=1e-5, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g) + wd * w
        acc_g, acc_d = state[0]._data, state[1]._data
        acc_g = self.rho * acc_g + (1 - self.rho) * g * g
        delta = jnp.sqrt(acc_d + self.epsilon) / \
            jnp.sqrt(acc_g + self.epsilon) * g
        acc_d = self.rho * acc_d + (1 - self.rho) * delta * delta
        return w - lr * delta, (acc_g, acc_d)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.9, momentum=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.rho = rho
        self.momentum = momentum
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_zeros_like_nd(weight), _zeros_like_nd(weight),
                    _zeros_like_nd(weight))
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g) + wd * w
        if self.centered:
            n, gbar, mom = (s._data for s in state)
            n = self.rho * n + (1 - self.rho) * g * g
            gbar = self.rho * gbar + (1 - self.rho) * g
            mom = self.momentum * mom - lr * g / jnp.sqrt(
                n - gbar * gbar + self.epsilon)
            new_w = w + mom
            out_state = (n, gbar, mom)
        else:
            n, mom = state[0]._data, state[1]._data
            n = self.rho * n + (1 - self.rho) * g * g
            mom = self.momentum * mom - lr * g / jnp.sqrt(n + self.epsilon)
            new_w = w + mom
            out_state = (n, mom)
        if self.clip_weights:
            new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
        return new_w, out_state


@register
class Ftrl(Optimizer):
    def __init__(self, learning_rate=0.1, lamda1=0.01, beta=1, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g)
        z, n = state[0]._data, state[1]._data
        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n + g * g
        new_w = jnp.where(
            jnp.abs(z) > self.lamda1,
            -(z - jnp.sign(z) * self.lamda1) /
            ((self.beta + jnp.sqrt(n)) / lr + wd), 0.0)
        return new_w, (z, n)


@register
class FTML(Optimizer):
    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight),
                _zeros_like_nd(weight))

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g) + wd * w
        d, v, z = (s._data for s in state)
        v = self.beta2 * v + (1 - self.beta2) * g * g
        d_t = (1 - self.beta1 ** t) / lr * \
            (jnp.sqrt(v / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d_t - self.beta1 * d
        z = self.beta1 * z + (1 - self.beta1) * g - sigma * w
        return -z / d_t, (d_t, v, z)


@register
class Signum(Optimizer):
    """signSGD with momentum (reference optimizer/signum.py)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return _zeros_like_nd(weight)
        return None

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g)
        if state is not None:
            mom = state._data
            mom = self.momentum * mom - (1 - self.momentum) * g
            new_w = (1 - lr * (wd + self.wd_lh)) * w + lr * jnp.sign(mom)
            return new_w, mom
        return (1 - lr * (wd + self.wd_lh)) * w - lr * jnp.sign(g), None


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer/sgld.py)."""

    def step(self, w, g, state, lr, wd, t):
        from .. import _rng
        g = self._prep(g) + wd * w
        noise = jax.random.normal(_rng.next_key(), w.shape,
                                  dtype=w.dtype) * math.sqrt(lr)
        return w - lr / 2 * g + noise, None


@register
class DCASGD(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight) if self.momentum != 0.0 else None,
                NDArray(weight._data, ctx=weight._ctx))

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g) + wd * w
        mom, prev = state
        prev_w = prev._data
        comp = self.lamda * g * g * (w - prev_w)
        if mom is not None:
            m = self.momentum * mom._data - lr * (g + comp)
            new_w = w + m
            mom._rebind(m)
        else:
            new_w = w - lr * (g + comp)
        prev._rebind(new_w)
        return new_w, state

    def _write_state(self, state, new_state):
        pass  # managed in step


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference optimizer/lars.py)."""

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return _zeros_like_nd(weight)
        return None

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g)
        w_norm = jnp.linalg.norm(w)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon), 1.0)
        g = g + wd * w
        if state is not None:
            mom = state._data
            mom = self.momentum * mom + trust * lr * g
            return w - mom, mom
        return w - trust * lr * g, None


@register
class LAMB(Optimizer):
    """Layer-wise Adam for large batches (reference optimizer/lamb.py,
    fused multi_lamb kernels src/operator/contrib/multi_lamb.cc)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kw):
        super().__init__(learning_rate=learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g)
        m, v = state[0]._data, state[1]._data
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        if self.bias_correction:
            mhat = m / (1 - self.beta1 ** t)
            vhat = v / (1 - self.beta2 ** t)
        else:
            mhat, vhat = m, v
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + wd * w
        w_norm = jnp.linalg.norm(w)
        r_norm = jnp.linalg.norm(r)
        if self.lower_bound is not None:
            w_norm = jnp.maximum(w_norm, self.lower_bound)
        if self.upper_bound is not None:
            w_norm = jnp.minimum(w_norm, self.upper_bound)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return w - lr * ratio * r, (m, v)


@register
class LANS(LAMB):
    """LAMB with per-step gradient normalization (reference
    optimizer/lans.py). The normalized gradient feeds LAMB's moment
    machinery; rescale/clip must apply exactly once, so the normalization
    happens here and LAMB's own _prep then operates on an already-scaled
    unit-norm gradient with rescale_grad temporarily neutralized."""

    def step(self, w, g, state, lr, wd, t):
        g = self._prep(g)
        g = g / jnp.maximum(jnp.linalg.norm(g), 1e-12)
        saved_rescale, saved_clip = self.rescale_grad, self.clip_gradient
        self.rescale_grad, self.clip_gradient = 1.0, None
        try:
            return super().step(w, g, state, lr, wd, t)
        finally:
            self.rescale_grad, self.clip_gradient = saved_rescale, saved_clip


class Updater:
    """KVStore-server-side updater wrapper (reference optimizer/updater.py).

    Keeps per-key state dict; used by `update_on_kvstore` mode and by the
    classic `mx.kvstore.KVStore.set_optimizer` path.
    """

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        import pickle
        return pickle.dumps((self.states, self.optimizer)
                            if dump_optimizer else self.states)

    def set_states(self, states):
        import pickle
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states


def get_updater(optimizer):
    return Updater(optimizer)
