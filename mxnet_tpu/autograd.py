"""``mx.autograd`` — imperative automatic differentiation.

Reference: ``python/mxnet/autograd.py`` (record:121, pause:145,
mark_variables:218, backward:245, grad:272, Function:369) over the C++
``Imperative`` singleton. Here the tape lives in :mod:`mxnet_tpu._tape`; the
per-op backward rules come from ``jax.vjp`` instead of nnvm FGradient
node-makers, and the ``MXGradient`` graph pass disappears.
"""

import contextlib

from . import _tape
from .ndarray.ndarray import NDArray


class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        if self._enter_is_record is not None:
            self._prev_is_record = _tape.set_recording(self._enter_is_record)
        if self._enter_train_mode is not None:
            self._prev_train_mode = _tape.set_training(self._enter_train_mode)
        return self

    def __exit__(self, *exc):
        if self._enter_is_record is not None:
            _tape.set_recording(self._prev_is_record)
        if self._enter_train_mode is not None:
            _tape.set_training(self._prev_train_mode)


def record(train_mode=True):
    """Scope in which executed ops are recorded for differentiation
    (reference autograd.py:121)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    """Scope in which recording is suspended (reference autograd.py:145)."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def is_recording():
    return _tape.is_recording()


def is_training():
    return _tape.is_training()


def set_recording(flag):
    return _tape.set_recording(flag)


def set_training(flag):
    return _tape.set_training(flag)


def mark_variables(variables, gradients, grad_reqs='write'):
    """Reference autograd.py:218."""
    if isinstance(variables, NDArray):
        variables, gradients = [variables], [gradients]
    _tape.mark_variables(variables, gradients, grad_reqs)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Reference autograd.py:245."""
    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None:
            head_grads = [head_grads]
    _tape.backward(heads, head_grads, retain_graph=retain_graph,
                   train_mode=train_mode)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Reference autograd.py:272 — returns grads instead of writing buffers.

    create_graph=True records the backward pass itself on the tape, so the
    returned gradients are differentiable (higher-order autograd —
    reference tests/python/unittest/test_higher_order_grad.py).
    """
    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None:
            head_grads = [head_grads]
    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    for v in variables:
        if v._ag is None or not v._ag.variable:
            raise ValueError('variables must be marked (attach_grad) and '
                             'used in the recorded computation')
    retain = retain_graph if retain_graph is not None else create_graph
    outs = _tape.backward(heads, head_grads, retain_graph=retain,
                          train_mode=train_mode, variables=variables,
                          create_graph=create_graph)
    return outs[0] if single else outs


def get_symbol(x):
    raise NotImplementedError(
        'autograd.get_symbol: graph export goes through HybridBlock.export')


class Function:
    """Custom differentiable function (reference autograd.py:369).

    Subclass and implement ``forward`` and ``backward``; backward receives
    output cotangents and returns input cotangents.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        import jax.numpy as jnp
        with pause():
            outputs = self.forward(*inputs)
        multi = isinstance(outputs, (tuple, list))
        out_list = list(outputs) if multi else [outputs]
        if _tape.is_recording() and _tape._needs_grad(list(inputs)):
            fnode = self

            def _fn(*raws):
                # placeholder pure fn; backward is overridden below
                return tuple(o._data for o in out_list) if multi else \
                    out_list[0]._data

            node = _tape.TapeNode(
                _fn, [x._data for x in inputs],
                [getattr(x, '_ag', None) for x in inputs],
                len(out_list), type(self).__name__,
                out_avals=[__import__('jax').typeof(o._data)
                           for o in out_list])

            def _custom_vjp(cots):
                if not isinstance(cots, (tuple, list)):
                    cots = (cots,)
                with pause():
                    ins = fnode.backward(*[NDArray(c) for c in cots])
                if isinstance(ins, NDArray):
                    ins = (ins,)
                return tuple(i._data if isinstance(i, NDArray) else i
                             for i in ins)

            node.vjp_fn = _custom_vjp
            for i, o in enumerate(out_list):
                o._ag = _tape.AGInfo(node=node, index=i)
        return outputs
