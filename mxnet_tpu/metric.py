"""``mx.metric`` / ``gluon.metric`` — evaluation metrics.

Reference: ``python/mxnet/gluon/metric.py`` (1,856 LoC). Metrics accumulate
host-side scalars; per-batch reductions run on device and sync once per
update (cheap — one scalar transfer).
"""

import numpy as _np

from .base import register as _register_factory, registry_create
from .ndarray.ndarray import NDArray


def _to_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


class EvalMetric:
    """Base metric (reference gluon/metric.py:EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label, pred):
        self.update(list(label.values()), list(pred.values()))

    def __str__(self):
        return f'EvalMetric: {dict(self.get_name_value())}'


register = _register_factory(EvalMetric)


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    return registry_create(EvalMetric, metric, *args, **kwargs)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name='composite', **kw):
        super().__init__(name, **kw)
        self.metrics = metrics or []

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, 'metrics', []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.append(name)
            values.append(value)
        return names, values


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name='accuracy', **kw):
        super().__init__(name, **kw)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label)
            pred = _to_np(pred)
            if pred.shape != label.shape:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype('int32').ravel()
            label = label.astype('int32').ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name='top_k_accuracy', **kw):
        super().__init__(f'{name}_{top_k}', **kw)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).astype('int32')
            pred = _to_np(pred)
            argsorted = _np.argsort(-pred, axis=-1)[..., :self.top_k]
            correct = (argsorted == label[..., None]).any(axis=-1)
            self.sum_metric += correct.sum()
            self.num_inst += correct.size


@register
class MAE(EvalMetric):
    def __init__(self, name='mae', **kw):
        super().__init__(name, **kw)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += _np.abs(label - pred.reshape(label.shape)).sum()
            self.num_inst += label.size


@register
class MSE(EvalMetric):
    def __init__(self, name='mse', **kw):
        super().__init__(name, **kw)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += ((label - pred.reshape(label.shape)) ** 2).sum()
            self.num_inst += label.size


@register
class RMSE(MSE):
    def __init__(self, name='rmse', **kw):
        EvalMetric.__init__(self, name, **kw)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, _np.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name='cross-entropy', **kw):
        super().__init__(name, **kw)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel().astype('int64')
            pred = _to_np(pred)
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name='perplexity', **kw):
        super().__init__(name=name, **kw)
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        if self.ignore_label is None:
            return super().update(labels, preds)
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel().astype('int64')
            pred = _to_np(pred).reshape(label.shape[0], -1)
            keep = label != self.ignore_label
            prob = pred[_np.arange(label.shape[0]), label][keep]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += int(keep.sum())

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, float(_np.exp(self.sum_metric / self.num_inst)))


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name='nll-loss', **kw):
        super().__init__(eps=eps, name=name, **kw)


@register
class F1(EvalMetric):
    """F1 score. ``average='macro'`` averages per-class F1 over observed
    classes (generalizes the reference, which rejects multiclass input);
    'micro' pools tp/fp/fn; 'binary' scores class 1 only."""

    def __init__(self, name='f1', average='macro', **kw):
        super().__init__(name, **kw)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self._tp, self._fp, self._fn = {}, {}, {}

    def reset(self):
        super().reset()
        self.reset_stats()

    def update(self, labels, preds):
        from collections import defaultdict
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel().astype('int32')
            pred = _to_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.ravel().astype('int32')
            for c in _np.union1d(_np.unique(label), _np.unique(pred)):
                c = int(c)
                self._tp[c] = self._tp.get(c, 0) + int(
                    ((pred == c) & (label == c)).sum())
                self._fp[c] = self._fp.get(c, 0) + int(
                    ((pred == c) & (label != c)).sum())
                self._fn[c] = self._fn.get(c, 0) + int(
                    ((pred != c) & (label == c)).sum())
            self.num_inst += 1

    #: F-beta weight; F1 is beta=1, Fbeta overrides (reference Fbeta
    #: subclasses F1 the same way)
    _beta = 1.0

    @staticmethod
    def _fbeta_score(tp, fp, fn, beta):
        prec = tp / max(tp + fp, 1)
        rec = tp / max(tp + fn, 1)
        b2 = beta * beta
        return (1 + b2) * prec * rec / max(b2 * prec + rec, 1e-12)

    def _f1_of(self, c):
        return self._fbeta_score(self._tp.get(c, 0), self._fp.get(c, 0),
                                 self._fn.get(c, 0), self._beta)

    def get(self):
        if self.average == 'micro':
            return (self.name, self._fbeta_score(
                sum(self._tp.values()), sum(self._fp.values()),
                sum(self._fn.values()), self._beta))
        if self.average == 'macro':
            classes = sorted(self._tp)
            if not classes:
                return (self.name, 0.0)
            return (self.name,
                    sum(self._f1_of(c) for c in classes) / len(classes))
        # binary (reference default): F1 of the positive class 1
        return (self.name, self._f1_of(1))


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (reference gluon/metric.py:MCC)."""

    def __init__(self, name='mcc', **kw):
        super().__init__(name, **kw)
        self._tp = self._fp = self._tn = self._fn = 0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._tn = self._fn = 0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel().astype('int32')
            pred = _to_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.ravel().astype('int32')
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._tn += ((pred == 0) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            self.num_inst += 1

    def get(self):
        tp, fp, tn, fn = self._tp, self._fp, self._tn, self._fn
        denom = _np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        mcc = (tp * tn - fp * fn) / denom if denom else 0.0
        return (self.name, mcc)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name='pearsonr', **kw):
        super().__init__(name, **kw)
        self._labels, self._preds = [], []

    def reset(self):
        super().reset()
        self._labels, self._preds = [], []

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._labels.append(_to_np(label).ravel())
            self._preds.append(_to_np(pred).ravel())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return (self.name, float('nan'))
        lab = _np.concatenate(self._labels)
        pre = _np.concatenate(self._preds)
        return (self.name, float(_np.corrcoef(lab, pre)[0, 1]))


@register
class Loss(EvalMetric):
    def __init__(self, name='loss', **kw):
        super().__init__(name, **kw)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = _to_np(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name='custom', allow_extra_outputs=False, **kw):
        super().__init__(f'{name}({feval.__name__})', **kw)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            reval = self._feval(_to_np(label), _to_np(pred))
            if isinstance(reval, tuple):
                m, n = reval
                self.sum_metric += m
                self.num_inst += n
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name='custom', allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (reference metric.py:np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


@register
class Fbeta(F1):
    """Reference metric.py:815 — harmonic precision/recall mean weighted
    by beta^2."""

    def __init__(self, name='fbeta', beta=1, average='binary', **kw):
        super().__init__(name=name, average=average, **kw)
        self.beta = beta
        self._beta = float(beta)


@register
class BinaryAccuracy(EvalMetric):
    """Reference metric.py:876 — accuracy of thresholded binary/multilabel
    predictions."""

    def __init__(self, name='binary_accuracy', threshold=0.5, **kw):
        super().__init__(name, **kw)
        self.threshold = threshold

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel()
            pred = (_to_np(pred).ravel() > self.threshold)
            self.sum_metric += float((pred == (label > 0.5)).sum())
            self.num_inst += label.size


@register
class MeanPairwiseDistance(EvalMetric):
    """Reference metric.py:1197 — mean p-norm distance over the last axis."""

    def __init__(self, name='mpd', p=2, **kw):
        super().__init__(name, **kw)
        self.p = p

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label)
            pred = _to_np(pred)
            d = (_np.abs(pred - label) ** self.p).sum(axis=-1) ** \
                (1.0 / self.p)
            self.sum_metric += float(d.sum())
            self.num_inst += int(d.size)


@register
class MeanCosineSimilarity(EvalMetric):
    """Reference metric.py:1263 — cosine similarity over the last axis."""

    def __init__(self, name='cos_sim', eps=1e-8, **kw):
        super().__init__(name, **kw)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label)
            pred = _to_np(pred)
            num = (label * pred).sum(axis=-1)
            den = _np.maximum(
                _np.linalg.norm(label, axis=-1) *
                _np.linalg.norm(pred, axis=-1), self.eps)
            sim = num / den
            self.sum_metric += float(sim.sum())
            self.num_inst += int(sim.size)


@register
class PCC(EvalMetric):
    """Reference metric.py:1586 — multiclass Matthews/Pearson correlation
    from the running confusion matrix."""

    def __init__(self, name='pcc', **kw):
        super().__init__(name, **kw)
        self._cm = _np.zeros((0, 0), dtype=_np.int64)

    def reset(self):
        super().reset()
        self._cm = _np.zeros((0, 0), dtype=_np.int64)

    def _grow(self, k):
        if k > self._cm.shape[0]:
            cm = _np.zeros((k, k), dtype=_np.int64)
            n = self._cm.shape[0]
            cm[:n, :n] = self._cm
            self._cm = cm

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).ravel().astype('int64')
            pred = _to_np(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.ravel().astype('int64')
            k = int(max(label.max(initial=0), pred.max(initial=0))) + 1
            self._grow(k)
            _np.add.at(self._cm, (label, pred), 1)
            self.num_inst += label.size

    def get(self):
        c = self._cm.astype(_np.float64)
        n = c.sum()
        if n == 0:
            return (self.name, float('nan'))
        t = c.sum(axis=1)            # true counts per class
        p = c.sum(axis=0)            # predicted counts per class
        cov_tp = (c.trace() * n - (t * p).sum())
        cov_tt = (n * n - (t * t).sum())
        cov_pp = (n * n - (p * p).sum())
        den = _np.sqrt(cov_tt * cov_pp)
        return (self.name, float(cov_tp / den) if den else float('nan'))


@register
class Torch(Loss):
    """Reference metric.py:1734 — dummy metric for torch criterions."""

    def __init__(self, name='torch', **kw):
        super().__init__(name=name, **kw)
