"""Out-of-tree extension loading.

Reference: ``python/mxnet/library.py`` ``load()`` → ``MXLoadLib`` — load a
dynamic library implementing custom ops / partitioners / graph passes via
the self-contained ``include/mxnet/lib_api.h`` ABI (1,313 LoC; examples at
example/extensions/lib_custom_op).

TPU re-design: the extension unit is a Python module (the registry it must
talk to — ops.registry, operator.register, symbol passes — lives in
Python; there is no C ABI boundary to cross). A ``.py`` path is executed
with the registration API in scope; a ``.so`` path is loaded with ctypes
and may expose an optional ``mxnet_tpu_lib_init`` entry point (for native
data-path extensions, e.g. custom RecordIO codecs).
"""

import ctypes
import os
import runpy

_loaded = {}


def load(path, verbose=True):
    """Load an extension library (reference library.py:load).

    Returns the module namespace (``.py``) or the CDLL handle (``.so``).
    """
    path = os.path.abspath(path)
    if path in _loaded:
        return _loaded[path]
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if path.endswith('.py'):
        ns = runpy.run_path(path)
        _loaded[path] = ns
        if verbose:
            import logging
            logging.info('loaded library %s (%d symbols)', path, len(ns))
        return ns
    if path.endswith(('.so', '.dylib')):
        lib = ctypes.CDLL(path, ctypes.RTLD_LOCAL)
        if hasattr(lib, 'mxnet_tpu_lib_init'):
            lib.mxnet_tpu_lib_init()
        _loaded[path] = lib
        return lib
    raise ValueError(
        f'unsupported extension type: {path} (expected .py or .so)')


def loaded_libraries():
    return dict(_loaded)
