"""``mx.telemetry`` — distributed tracing, unified metrics and the
flight recorder.

Zero-dependency observability for the whole stack (serving tier,
dist_async training, elastic checkpoints):

* **Spans + context propagation** (:mod:`.trace`): ``with
  telemetry.span('train.step', step=i): ...`` — spans nest via
  thread-local context, cross process boundaries as one optional
  ``tc`` field on every RPC envelope (injected by ``RpcClient``,
  adopted by ``RpcServer``), and land in a bounded per-process ring
  buffer (the flight recorder). One user request through the router =
  one connected trace: routing → retry/failover attempts → replica
  admission → queue wait → prefill chunks → per-step decode.
* **Metrics registry** (:mod:`.metrics`): Counter / Gauge / Histogram
  with fixed mergeable log-scale buckets; the serving/RPC/training
  ``stats()`` surfaces register into it, the router aggregates
  fleet-wide over the RPC ``metrics`` verb, and
  :func:`render_prometheus` emits the text exposition format.
* **Export** (:mod:`.export`): Chrome-trace/Perfetto JSON with
  cross-process clock normalization off RPC ping timestamps, plus the
  span-tree formatter behind ``tools/trace_dump.py``.

Env knobs: ``MXNET_TELEMETRY`` (default on; ``0`` disables tracing —
the disabled path is a near-no-op), ``MXNET_TELEMETRY_BUFFER`` (ring
capacity, default 4096 events), ``MXNET_TELEMETRY_SAMPLE`` (root-span
sampling fraction, default 1.0). See docs/observability.md.
"""

from . import trace
from . import metrics
from . import export

from .trace import (span, child_span, attach, emit, current_tc, enabled,
                    configure, events, clear, snapshot_buffer,
                    note_clock, clock_offsets, proc_name, walltime)
from .metrics import (Counter, Gauge, Histogram, Reservoir,
                      MetricsRegistry, default_registry, counter, gauge,
                      histogram, register_collector,
                      unregister_collector, merge_snapshots,
                      render_prometheus)
from .export import (merge_buffers, trace_ids, trace_tree, format_tree,
                     chrome_doc, export_chrome_trace, dump_json)

__all__ = [
    'trace', 'metrics', 'export',
    # spans / flight recorder
    'span', 'child_span', 'attach', 'emit', 'current_tc', 'enabled',
    'configure', 'events', 'clear', 'snapshot_buffer', 'note_clock',
    'clock_offsets', 'proc_name', 'walltime',
    # metrics
    'Counter', 'Gauge', 'Histogram', 'Reservoir', 'MetricsRegistry',
    'default_registry', 'counter', 'gauge', 'histogram',
    'register_collector', 'unregister_collector', 'merge_snapshots',
    'render_prometheus',
    # export
    'merge_buffers', 'trace_ids', 'trace_tree', 'format_tree',
    'chrome_doc', 'export_chrome_trace', 'dump_json',
]
