"""Trace export: multi-process buffer merge, span trees, Chrome trace.

The flight recorder (``trace.py``) is per-process; a distributed
request leaves spans in every process it touched. This module merges
buffer snapshots (the local one plus any collected over the RPC
``telemetry`` verb — see ``Router.fleet_telemetry()``) into one event
list on one clock:

* **dedup** by ``(recorder id, seq)`` — in-process replica clusters
  return the SAME buffer from every endpoint, and a fleet sweep must
  count each recorder once;
* **clock normalization** — events from a remote process are shifted
  by the offset measured off RPC ping timestamps
  (``trace.note_clock``), so spans line up across machines to within
  half a ping RTT.

Outputs: :func:`trace_tree` (parent-edge resolution for tests and the
``tools/trace_dump.py`` pretty printer), :func:`chrome_doc` /
:func:`export_chrome_trace` (the ``chrome://tracing`` / Perfetto JSON
format the reference profiler also targets), and :func:`dump_json`
(the raw merged buffer ``trace_dump`` reads back).
"""

import json

from . import trace as _trace

__all__ = ['merge_buffers', 'trace_ids', 'trace_tree', 'format_tree',
           'chrome_doc', 'export_chrome_trace', 'dump_json']


def merge_buffers(buffers, offsets=None):
    """Merge buffer snapshots into one time-sorted event list.
    ``buffers`` are :func:`trace.snapshot_buffer` dicts; ``offsets``
    maps proc name -> seconds its clock runs ahead of ours (default:
    the offsets measured off ping replies)."""
    if offsets is None:
        offsets = _trace.clock_offsets()
    local = _trace.proc_name()
    seen = set()
    out = []
    for buf in buffers:
        if not buf:
            continue
        rid = buf.get('recorder') or buf.get('proc')
        proc = buf.get('proc')
        off = 0.0 if proc == local else float(offsets.get(proc, 0.0))
        for rec in buf.get('events', ()):
            if rec is None:
                continue
            key = (rid, rec.get('seq'))
            if key in seen:
                continue
            seen.add(key)
            if off:
                rec = dict(rec)
                rec['t0'] -= off
                rec['t1'] -= off
            out.append(rec)
    out.sort(key=lambda r: (r.get('t0', 0.0), r.get('seq', 0)))
    return out


def trace_ids(events):
    """Trace ids present, most recent root first (roots are spans with
    no parent); traces whose root was overwritten in the ring come
    last, in first-seen order."""
    roots = []
    rest = []
    seen = set()
    for rec in events:
        tid = rec.get('trace')
        if tid in seen:
            continue
        if rec.get('parent') is None:
            seen.add(tid)
            roots.append(tid)
        else:
            rest.append(tid)
    roots.reverse()
    for tid in rest:
        if tid not in seen:
            seen.add(tid)
            roots.append(tid)
    return roots


def trace_tree(events, trace_id):
    """Build the span tree of one trace: returns a list of root nodes
    ``{'rec': record, 'children': [...]}``, children sorted by start
    time. Spans whose parent is missing from the event set (ring
    overwrite, uncollected process) surface as extra roots — a fully
    connected trace has exactly one."""
    spans = [r for r in events if r.get('trace') == trace_id]
    nodes = {r['span']: {'rec': r, 'children': []} for r in spans}
    roots = []
    for r in spans:
        parent = r.get('parent')
        if parent is not None and parent in nodes:
            nodes[parent]['children'].append(nodes[r['span']])
        else:
            roots.append(nodes[r['span']])
    for node in nodes.values():
        node['children'].sort(key=lambda n: n['rec'].get('t0', 0.0))
    roots.sort(key=lambda n: n['rec'].get('t0', 0.0))
    return roots


def format_tree(events, trace_id):
    """Human-readable span tree of one trace (the trace_dump CLI)."""
    roots = trace_tree(events, trace_id)
    if not roots:
        return f'trace {trace_id}: no spans'
    t_base = roots[0]['rec'].get('t0', 0.0)
    lines = [f'trace {trace_id} '
             f'({sum(1 for e in events if e.get("trace") == trace_id)} '
             f'spans)']

    def _walk(node, depth):
        r = node['rec']
        dur_ms = (r.get('t1', 0.0) - r.get('t0', 0.0)) * 1e3
        at_ms = (r.get('t0', 0.0) - t_base) * 1e3
        attrs = r.get('attrs') or {}
        extra = ' '.join(f'{k}={v}' for k, v in sorted(attrs.items()))
        lines.append(
            f'  {"  " * depth}{r.get("name", "?"):<28} '
            f'+{at_ms:9.3f}ms {dur_ms:9.3f}ms  '
            f'[{r.get("proc", "?")}/{r.get("thread", "?")}]'
            + (f'  {extra}' if extra else ''))
        for child in node['children']:
            _walk(child, depth + 1)

    for root in roots:
        _walk(root, 0)
    return '\n'.join(lines)


def chrome_doc(events):
    """Chrome-trace JSON document ('X' complete events, µs timestamps,
    process/thread metadata) from a merged event list."""
    pids, tids = {}, {}
    trace_events = []
    for rec in events:
        proc = rec.get('proc', '?')
        thread = rec.get('thread', '?')
        pid = pids.setdefault(proc, len(pids) + 1)
        tid = tids.setdefault((proc, thread), len(tids) + 1)
        args = {'trace': rec.get('trace'), 'span': rec.get('span')}
        if rec.get('parent') is not None:
            args['parent'] = rec['parent']
        args.update(rec.get('attrs') or {})
        trace_events.append({
            'name': rec.get('name', '?'), 'ph': 'X', 'cat': 'telemetry',
            'ts': rec.get('t0', 0.0) * 1e6,
            'dur': max(0.0, (rec.get('t1', 0.0) - rec.get('t0', 0.0))
                       * 1e6),
            'pid': pid, 'tid': tid, 'args': args})
    for proc, pid in pids.items():
        trace_events.append({'name': 'process_name', 'ph': 'M',
                             'pid': pid, 'tid': 0,
                             'args': {'name': proc}})
    for (proc, thread), tid in tids.items():
        trace_events.append({'name': 'thread_name', 'ph': 'M',
                             'pid': pids[proc], 'tid': tid,
                             'args': {'name': thread}})
    return {'traceEvents': trace_events, 'displayTimeUnit': 'ms'}


def export_chrome_trace(path, extra_buffers=()):
    """Write this process's flight recorder (merged with any extra
    buffer snapshots — e.g. ``Router.fleet_telemetry()``) as a Chrome
    trace; open in ``chrome://tracing`` or https://ui.perfetto.dev.
    Returns ``path``."""
    buffers = [_trace.snapshot_buffer()] + list(extra_buffers)
    events = merge_buffers(buffers)
    with open(path, 'w') as f:
        json.dump(chrome_doc(events), f)
    return path


def dump_json(path, extra_buffers=()):
    """Write the raw merged buffers (events + clock offsets) as JSON —
    the ``tools/trace_dump.py`` input format. Returns ``path``."""
    buffers = [_trace.snapshot_buffer()] + list(extra_buffers)
    doc = {'proc': _trace.proc_name(),
           'clock_offsets': _trace.clock_offsets(),
           'events': merge_buffers(buffers)}
    with open(path, 'w') as f:
        json.dump(doc, f)
    return path
