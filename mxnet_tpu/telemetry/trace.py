"""Spans, trace context and the flight recorder (``mx.telemetry``).

Dapper-style distributed tracing with zero dependencies:

* a **span** is one timed region ``(trace_id, span_id, parent_id,
  t_start, t_end, attrs)``; :func:`span` opens one as a context
  manager, :func:`emit` records one retroactively (schedulers that
  learn a region's start time only when it ends — queue waits).
* **trace context** is thread-local ``(trace_id, span_id)``; a span
  installs itself as the context for its body, so nested spans chain
  parent edges automatically. :func:`current_tc` exports the context
  as a small JSON-safe dict (the ``tc`` field on RPC envelopes) and
  :func:`attach` adopts one on the receiving side — that is the entire
  propagation protocol.
* the **flight recorder** is a bounded per-process ring buffer
  (``MXNET_TELEMETRY_BUFFER`` events, default 4096): the newest spans
  are always retained, the oldest silently overwritten, so tracing can
  stay on in production and a postmortem reads the last few thousand
  events. :func:`snapshot_buffer` serializes it for the RPC
  ``telemetry`` verb and the Chrome-trace exporter.

Timestamps are wall-clock (``time.time()``) so buffers from different
processes land on one axis; per-peer clock offsets measured off RPC
ping replies (:func:`note_clock`) let the exporter normalize them.

``MXNET_TELEMETRY=0`` disables tracing: :func:`span` returns a shared
no-op context manager, :func:`current_tc` returns ``None`` after a
single flag check — the disabled path is a near-no-op, machine-checked
by the overhead guard in ``tests/test_telemetry.py``.
``MXNET_TELEMETRY_SAMPLE`` (default 1.0) samples ROOT spans: an
unsampled root records nothing and propagates nothing, while children
of a live context always record (a trace is all-or-nothing).

Locking: the recorder lock is level ``telemetry.buffer`` — below every
runtime lock in the declared hierarchy (``analysis/locks.py``), so a
span may be recorded while holding any other lock; nothing is ever
acquired under it.
"""

import os
import random
import threading
import time

__all__ = ['span', 'child_span', 'attach', 'emit', 'current_tc',
           'enabled', 'configure', 'events', 'clear', 'snapshot_buffer',
           'note_clock', 'clock_offsets', 'proc_name', 'walltime']

_FALSY = ('0', 'false', 'off', 'no')


def _env_enabled():
    return os.environ.get('MXNET_TELEMETRY', '1').strip().lower() \
        not in _FALSY


def _env_buffer():
    try:
        n = int(os.environ.get('MXNET_TELEMETRY_BUFFER', '') or 4096)
    except ValueError:
        n = 4096
    return max(16, n)


def _env_sample():
    try:
        s = float(os.environ.get('MXNET_TELEMETRY_SAMPLE', '') or 1.0)
    except ValueError:
        s = 1.0
    return min(1.0, max(0.0, s))


#: stable identity of this process in every record and buffer snapshot
_PROC = f'proc-{os.getpid()}'

_enabled = _env_enabled()
_sample = _env_sample()

def _maybe_tracked(lock, level):
    """Race-checker wrapping, import-robust: this module is imported
    early in package init (via kvstore/rpc.py) and must also load
    standalone (tools/), so the analysis import may not be available —
    an untracked lock is the correct degradation either way."""
    if os.environ.get('MXNET_RACE_CHECK', '').strip() in ('', '0'):
        return lock
    try:
        from ..analysis import race as _race
        if _race.enabled():
            return _race.tracked(lock, level)
    except Exception:
        pass
    return lock


_lock = _maybe_tracked(threading.Lock(), 'telemetry.buffer')

_ring = [None] * _env_buffer()
_seq = 0                                # total records ever appended
_offsets = {}                           # peer proc name -> clock offset (s)

_tls = threading.local()

#: recorder identity: dedups buffers when several RPC peers live in one
#: process (in-process tests) and the fleet sweep collects each once
_RECORDER = f'{_PROC}-{os.urandom(4).hex()}'

walltime = time.time


def proc_name():
    return _PROC


def enabled():
    return _enabled


def configure(enabled=None, buffer=None, sample=None):
    """Runtime reconfiguration (tests; production uses the env knobs
    ``MXNET_TELEMETRY`` / ``MXNET_TELEMETRY_BUFFER`` /
    ``MXNET_TELEMETRY_SAMPLE`` read at import). Resizing the buffer
    drops recorded events."""
    global _enabled, _sample, _ring, _seq
    with _lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if sample is not None:
            _sample = min(1.0, max(0.0, float(sample)))
        if buffer is not None:
            _ring = [None] * max(16, int(buffer))
            _seq = 0


def _rng():
    r = getattr(_tls, 'rng', None)
    if r is None:
        r = _tls.rng = random.Random(
            int.from_bytes(os.urandom(8), 'big'))
    return r


def _new_id():
    return '%016x' % _rng().getrandbits(64)


def _record(name, trace_id, span_id, parent_id, t0, t1, attrs):
    rec = {'name': name, 'trace': trace_id, 'span': span_id,
           'parent': parent_id, 't0': t0, 't1': t1, 'proc': _PROC,
           'thread': threading.current_thread().name}
    if attrs:
        rec['attrs'] = attrs
    global _seq
    with _lock:
        rec['seq'] = _seq
        _ring[_seq % len(_ring)] = rec
        _seq += 1
    return rec


class _NoopSpan:
    """Shared do-nothing span: the entire disabled/unsampled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ('name', 'trace_id', 'span_id', 'parent_id', 'attrs',
                 't0', '_prev')

    def __init__(self, name, trace_id, parent_id, attrs):
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.attrs = attrs

    def set(self, **attrs):
        self.attrs.update(attrs)

    def __enter__(self):
        self.span_id = _new_id()
        self._prev = getattr(_tls, 'ctx', None)
        _tls.ctx = (self.trace_id, self.span_id)
        self.t0 = walltime()
        return self

    def __exit__(self, etype, exc, tb):
        t1 = walltime()
        _tls.ctx = self._prev
        if etype is not None:
            self.attrs['error'] = f'{etype.__name__}: {exc}'
        _record(self.name, self.trace_id, self.span_id, self.parent_id,
                self.t0, t1, self.attrs)
        return False


def span(name, parent=None, **attrs):
    """Open a span as a context manager. Child of the current context
    when one exists (or of ``parent``, a ``tc`` dict, when given);
    otherwise the root of a new trace, subject to
    ``MXNET_TELEMETRY_SAMPLE``. The span records on exit; an exception
    in the body lands in ``attrs['error']`` and propagates."""
    if not _enabled:
        return _NOOP
    if parent is not None:
        return _Span(name, str(parent.get('t')), str(parent.get('s')),
                     attrs)
    cur = getattr(_tls, 'ctx', None)
    if cur is not None:
        return _Span(name, cur[0], cur[1], attrs)
    if _sample < 1.0 and _rng().random() >= _sample:
        return _NOOP
    return _Span(name, _new_id(), None, attrs)


def child_span(name, **attrs):
    """Like :func:`span` but a no-op when there is no current context:
    instrumentation for hot library paths (kvstore push/pull) that
    should only trace inside a caller-opened trace, never start one."""
    if not _enabled:
        return _NOOP
    cur = getattr(_tls, 'ctx', None)
    if cur is None:
        return _NOOP
    return _Span(name, cur[0], cur[1], attrs)


class _Attach:
    __slots__ = ('_tc', '_prev')

    def __init__(self, tc):
        self._tc = tc

    def __enter__(self):
        self._prev = getattr(_tls, 'ctx', None)
        tc = self._tc
        if tc:
            _tls.ctx = (str(tc.get('t')), str(tc.get('s')))
        return self

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


def attach(tc):
    """Adopt a propagated trace context (``tc`` dict off an RPC
    envelope) as the current context for the body — the server side of
    context propagation. Falsy ``tc`` (or disabled telemetry) attaches
    nothing; always returns a context manager."""
    return _Attach(tc if (_enabled and tc) else None)


def current_tc():
    """The current context as a wire-safe dict ``{'t': trace_id, 's':
    span_id}``, or ``None`` — what ``RpcClient`` injects as the
    envelope's ``tc`` field."""
    if not _enabled:
        return None
    cur = getattr(_tls, 'ctx', None)
    if cur is None:
        return None
    return {'t': cur[0], 's': cur[1]}


def emit(name, t0, t1, parent=None, **attrs):
    """Record a completed span retroactively: ``parent`` is a ``tc``
    dict (a queued request's captured context) or, when ``None``, the
    current context. Returns the record, or ``None`` when nothing was
    recorded (disabled, or no parent and no context — retroactive
    spans never root a trace)."""
    if not _enabled:
        return None
    if parent is not None:
        trace_id, parent_id = str(parent.get('t')), str(parent.get('s'))
    else:
        cur = getattr(_tls, 'ctx', None)
        if cur is None:
            return None
        trace_id, parent_id = cur
    return _record(name, trace_id, _new_id(), parent_id,
                   float(t0), float(t1), attrs)


def events():
    """Snapshot of the flight recorder, oldest first."""
    with _lock:
        n, ring = _seq, _ring
        cap = len(ring)
        if n <= cap:
            return list(ring[:n])
        i = n % cap
        return ring[i:] + ring[:i]


def clear():
    """Drop every recorded event (tests; clock offsets survive)."""
    global _seq
    with _lock:
        for i in range(len(_ring)):
            _ring[i] = None
        _seq = 0


def snapshot_buffer():
    """Serializable flight-recorder snapshot: the payload of the RPC
    ``telemetry`` verb and the exporter's merge unit."""
    return {'proc': _PROC, 'recorder': _RECORDER, 'clock': walltime(),
            'events': events()}


def note_clock(proc, remote_ts, t_send, t_recv):
    """Record a peer's clock offset from one RPC round trip: the peer
    stamped ``remote_ts`` (its wall clock) between our ``t_send`` and
    ``t_recv`` — the midpoint estimate is NTP's, good to half the RTT,
    plenty for trace alignment. Our own proc is always offset 0."""
    if proc == _PROC:
        return
    off = float(remote_ts) - (float(t_send) + float(t_recv)) / 2.0
    with _lock:
        _offsets[proc] = off


def clock_offsets():
    """``{peer proc name: seconds ahead of our clock}``."""
    with _lock:
        return dict(_offsets)
