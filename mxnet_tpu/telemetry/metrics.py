"""Unified metrics registry: Counter / Gauge / Histogram + Prometheus
text exposition (``mx.telemetry.metrics``).

One process-wide :class:`MetricsRegistry` (``default_registry()``)
replaces the seven ad-hoc ``stats()`` dicts across the serving tier,
the RPC transport and elastic training — those dicts remain as thin
views, but the registry is the aggregation surface: every instrument
serializes to a JSON-safe snapshot, snapshots from different processes
**merge** (the router's ``fleet_metrics()`` over the RPC ``metrics``
verb), and :func:`render_prometheus` emits the text exposition format.

Design points:

* instruments are keyed by ``name{label="value",...}`` exactly as
  Prometheus renders them, so snapshot keys merge across processes by
  string identity;
* :class:`Histogram` uses FIXED log-scale bucket bounds (powers of two
  from 2^-20 s to 2^24) shared by every histogram ever created —
  merging is elementwise addition of counts, no bound negotiation.
  Percentiles are nearest-rank over the cumulative counts, clamped to
  the observed min/max (a single sample reports itself exactly);
* **collectors** are zero-arg callables yielding ``(kind, name,
  labels, value)`` samples at scrape time — how the existing
  ``stats()`` surfaces register without restructuring their locking.
  Collectors run OUTSIDE the registry lock (they take their owners'
  locks, which sit above ``telemetry.metrics`` in the hierarchy);
* :class:`Reservoir` (Vitter's Algorithm R) gives bounded-memory
  whole-run percentile samples — ``ServingMetrics`` uses it instead of
  sliding-window deques, and ``ElasticTrainer`` instead of unbounded
  lists.

Locking: one module lock at level ``telemetry.metrics`` (below every
runtime lock, above nothing) guards instrument values and the registry
tables. :class:`Reservoir` is deliberately unlocked — its owners
already serialize updates under their own leaf locks.
"""

import bisect
import math
import random
import threading

from . import trace as _trace

__all__ = ['Counter', 'Gauge', 'Histogram', 'Reservoir',
           'MetricsRegistry', 'default_registry', 'counter', 'gauge',
           'histogram', 'register_collector', 'unregister_collector',
           'merge_snapshots', 'render_prometheus', 'BUCKET_BOUNDS']

#: fixed log2-scale bucket upper bounds, identical for every histogram:
#: ~1 µs to ~1.9e7 (seconds, but unit-agnostic); one overflow bucket on
#: top. Fixed bounds are what make counts mergeable across processes.
BUCKET_BOUNDS = tuple(2.0 ** e for e in range(-20, 25))

_LOCK = _trace._maybe_tracked(threading.Lock(), 'telemetry.metrics')


def _key(name, labels):
    if not labels:
        return name
    inner = ','.join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f'{name}{{{inner}}}'


class Counter:
    """Monotonic counter (float increments allowed)."""

    __slots__ = ('key', '_v')

    def __init__(self, key):
        self.key = key
        self._v = 0

    def inc(self, n=1):
        with _LOCK:
            self._v += n

    @property
    def value(self):
        with _LOCK:
            return self._v


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ('key', '_v')

    def __init__(self, key):
        self.key = key
        self._v = 0

    def set(self, v):
        with _LOCK:
            self._v = v

    def inc(self, n=1):
        with _LOCK:
            self._v += n

    def dec(self, n=1):
        with _LOCK:
            self._v -= n

    @property
    def value(self):
        with _LOCK:
            return self._v


class Histogram:
    """Fixed-bucket log-scale histogram; mergeable by construction."""

    __slots__ = ('key', '_counts', '_sum', '_count', '_min', '_max')

    def __init__(self, key=''):
        self.key = key
        self._counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(BUCKET_BOUNDS, v)
        with _LOCK:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self):
        with _LOCK:
            return self._count

    @property
    def sum(self):
        with _LOCK:
            return self._sum

    def snapshot(self):
        with _LOCK:
            return {'counts': list(self._counts), 'sum': self._sum,
                    'count': self._count,
                    'min': self._min if self._count else 0.0,
                    'max': self._max if self._count else 0.0}

    def percentile(self, q):
        return _hist_percentile(self.snapshot(), q)

    def percentiles(self, qs=(50, 95, 99)):
        snap = self.snapshot()
        return {q: _hist_percentile(snap, q) for q in qs}


def _hist_percentile(snap, q):
    """Nearest-rank percentile off a histogram snapshot: the upper
    bound of the bucket holding the rank, clamped to the observed
    [min, max] so sparse histograms degrade gracefully (one sample
    reports exactly itself)."""
    n = snap['count']
    if not n:
        return 0.0
    lo, hi = snap['min'], snap['max']
    rank = min(n - 1, int(round(q / 100.0 * (n - 1))))
    cum = 0
    for i, c in enumerate(snap['counts']):
        cum += c
        if cum > rank:
            ub = BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS) else hi
            return min(max(ub, lo), hi)
    return hi


def merge_histograms(a, b):
    """Elementwise merge of two histogram snapshots (fixed bounds)."""
    return {'counts': [x + y for x, y in zip(a['counts'], b['counts'])],
            'sum': a['sum'] + b['sum'],
            'count': a['count'] + b['count'],
            'min': min(a['min'], b['min']) if (a['count'] and b['count'])
            else (a['min'] if a['count'] else b['min']),
            'max': max(a['max'], b['max']) if (a['count'] and b['count'])
            else (a['max'] if a['count'] else b['max'])}


class Reservoir:
    """Vitter's Algorithm R: a fixed-size uniform sample over an
    unbounded stream, plus exact running count/sum/min/max. NOT
    internally locked — owners update under their own (leaf) lock."""

    __slots__ = ('k', '_buf', '_n', '_sum', '_min', '_max', '_rng')

    def __init__(self, k=2048, seed=0x5EED):
        self.k = int(k)
        self._buf = []
        self._n = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._rng = random.Random(seed)

    def add(self, v):
        v = float(v)
        self._n += 1
        self._sum += v
        if v < self._min:
            self._min = v
        if v > self._max:
            self._max = v
        if len(self._buf) < self.k:
            self._buf.append(v)
        else:
            j = self._rng.randrange(self._n)
            if j < self.k:
                self._buf[j] = v

    def extend(self, vals):
        for v in vals:
            self.add(v)

    def samples(self):
        return list(self._buf)

    def __len__(self):
        return self._n

    @property
    def count(self):
        return self._n

    @property
    def sum(self):
        return self._sum

    @property
    def mean(self):
        return self._sum / self._n if self._n else 0.0

    @property
    def min(self):
        return self._min if self._n else 0.0

    @property
    def max(self):
        return self._max if self._n else 0.0


class MetricsRegistry:
    """Instruments + collectors; snapshots merge across processes."""

    def __init__(self):
        import os
        self._rid = f'reg-{os.getpid()}-{os.urandom(4).hex()}'
        self._metrics = {}              # key -> (kind, instrument)
        self._collectors = {}           # collector key -> fn

    # -------------------------------------------------------- instruments
    def _get(self, kind, cls, name, labels):
        key = _key(name, labels)
        with _LOCK:
            got = self._metrics.get(key)
            if got is not None:
                if got[0] != kind:
                    raise TypeError(
                        f'metric {key!r} already registered as {got[0]}')
                return got[1]
            inst = cls(key)
            self._metrics[key] = (kind, inst)
            return inst

    def counter(self, name, **labels):
        return self._get('counter', Counter, name, labels)

    def gauge(self, name, **labels):
        return self._get('gauge', Gauge, name, labels)

    def histogram(self, name, **labels):
        return self._get('histogram', Histogram, name, labels)

    # --------------------------------------------------------- collectors
    def register_collector(self, key, fn):
        """Register a scrape-time sample source (zero-arg callable
        yielding ``(kind, name, labels, value)``; kind ``'counter'`` or
        ``'gauge'``). Suffixes the key on collision; returns the final
        key (pass it to :meth:`unregister_collector`)."""
        with _LOCK:
            base, n = key, 1
            while key in self._collectors:
                n += 1
                key = f'{base}#{n}'
            self._collectors[key] = fn
        return key

    def unregister_collector(self, key):
        with _LOCK:
            self._collectors.pop(key, None)

    # ----------------------------------------------------------- snapshot
    def snapshot(self):
        """JSON-safe point-in-time view of every instrument plus every
        collector's samples. Collector callables run OUTSIDE the
        registry lock — they take their owners' locks, which sit above
        ``telemetry.metrics`` in the declared hierarchy."""
        out = {'proc': _trace.proc_name(), 'rid': self._rid,
               'counters': {}, 'gauges': {}, 'histograms': {}}
        with _LOCK:
            items = list(self._metrics.values())
            collectors = list(self._collectors.values())
        for kind, inst in items:
            if kind == 'counter':
                out['counters'][inst.key] = inst.value
            elif kind == 'gauge':
                out['gauges'][inst.key] = inst.value
            else:
                out['histograms'][inst.key] = inst.snapshot()
        for fn in collectors:
            try:
                samples = fn()
            except Exception:   # a closed/broken owner must not kill scrape
                continue
            for kind, name, labels, value in samples:
                key = _key(name, labels)
                if kind == 'counter':
                    out['counters'][key] = \
                        out['counters'].get(key, 0) + value
                else:
                    out['gauges'][key] = value
        return out


_DEFAULT = MetricsRegistry()


def default_registry():
    return _DEFAULT


def counter(name, **labels):
    return _DEFAULT.counter(name, **labels)


def gauge(name, **labels):
    return _DEFAULT.gauge(name, **labels)


def histogram(name, **labels):
    return _DEFAULT.histogram(name, **labels)


def register_collector(key, fn):
    return _DEFAULT.register_collector(key, fn)


def unregister_collector(key):
    _DEFAULT.unregister_collector(key)


def merge_snapshots(snaps):
    """Merge registry snapshots fleet-wide: counters and histogram
    buckets sum, gauges last-write-wins. Snapshots with a repeated
    registry id (``rid``) are counted ONCE — in-process replica
    clusters share one registry, and double-counting a shared registry
    would inflate every counter by the replica count."""
    seen = set()
    out = {'counters': {}, 'gauges': {}, 'histograms': {}}
    for s in snaps:
        if not s:
            continue
        rid = s.get('rid')
        if rid is not None:
            if rid in seen:
                continue
            seen.add(rid)
        for k, v in s.get('counters', {}).items():
            out['counters'][k] = out['counters'].get(k, 0) + v
        for k, v in s.get('gauges', {}).items():
            out['gauges'][k] = v
        for k, h in s.get('histograms', {}).items():
            prev = out['histograms'].get(k)
            out['histograms'][k] = h if prev is None \
                else merge_histograms(prev, h)
    return out


def _fmt(v):
    if isinstance(v, float):
        return f'{v:.10g}'
    return str(v)


def _split_key(key):
    i = key.find('{')
    if i < 0:
        return key, ''
    return key[:i], key[i:]


def _with_label(key, extra):
    name, labels = _split_key(key)
    if not labels:
        return f'{name}{{{extra}}}'
    return f'{name}{{{labels[1:-1]},{extra}}}'


def render_prometheus(snapshot=None):
    """Prometheus text exposition of a registry snapshot (default: this
    process's registry; pass ``Router.fleet_metrics()`` output for the
    fleet-wide view)."""
    snap = _DEFAULT.snapshot() if snapshot is None else snapshot
    lines = []
    typed = set()

    def _type_line(key, kind):
        name, _ = _split_key(key)
        if name not in typed:
            typed.add(name)
            lines.append(f'# TYPE {name} {kind}')

    for key in sorted(snap.get('counters', {})):
        _type_line(key, 'counter')
        lines.append(f'{key} {_fmt(snap["counters"][key])}')
    for key in sorted(snap.get('gauges', {})):
        _type_line(key, 'gauge')
        lines.append(f'{key} {_fmt(snap["gauges"][key])}')
    for key in sorted(snap.get('histograms', {})):
        h = snap['histograms'][key]
        _type_line(key, 'histogram')
        name, labels = _split_key(key)
        cum = 0
        for i, c in enumerate(h['counts']):
            cum += c
            if not c and i < len(BUCKET_BOUNDS):
                continue            # sparse: only emit occupied buckets
            le = _fmt(BUCKET_BOUNDS[i]) if i < len(BUCKET_BOUNDS) \
                else '+Inf'
            lines.append('%s %d' % (
                _with_label(name + '_bucket' + labels,
                            'le="%s"' % le), cum))
        lines.append(f'{name}_sum{labels} {_fmt(h["sum"])}')
        lines.append(f'{name}_count{labels} {h["count"]}')
    return '\n'.join(lines) + '\n'
