"""``mx.image`` — image I/O and augmentation.

Reference: ``python/mxnet/image/image.py`` (ImageIter:1285 + augmenters) and
the C++ decode path (src/io/image_aug_default.cc). Decode runs host-side
(cv2/PIL); augmentation ops run as registered ops so they can execute on
device inside the input pipeline.
"""

import numpy as _np

from ..ndarray.ndarray import NDArray, array


def imread(filename, flag=1, to_rgb=True):
    """Reference image.py:imread."""
    try:
        import cv2
        img = cv2.imread(filename, flag)
        if img is None:
            raise OSError(f'cannot read {filename}')
        if to_rgb and img.ndim == 3:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    except ImportError:
        from PIL import Image
        img = _np.asarray(Image.open(filename).convert(
            'RGB' if flag else 'L'))
    return array(img)


def imdecode(buf, flag=1, to_rgb=True):
    """Reference image.py:imdecode."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    try:
        import cv2
        img = cv2.imdecode(_np.frombuffer(buf, dtype=_np.uint8), flag)
        if to_rgb and img is not None and img.ndim == 3:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    except ImportError:
        import io
        from PIL import Image
        img = _np.asarray(Image.open(io.BytesIO(buf)))
    return array(img)


def imresize(src, w, h, interp=1):
    import jax.image
    raw = src._data if isinstance(src, NDArray) else src
    method = {0: 'nearest', 1: 'linear', 2: 'cubic'}.get(interp, 'linear')
    out = jax.image.resize(raw.astype('float32'), (h, w) + tuple(
        raw.shape[2:]), method)
    return NDArray(out)


def resize_short(src, size, interp=2):
    """Reference image.py:resize_short."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size if isinstance(size, (tuple, list)) else (size, size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    return fixed_crop(src, x0, y0, new_w, new_h), (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size if isinstance(size, (tuple, list)) else (size, size)
    x0 = _np.random.randint(0, max(w - new_w, 0) + 1)
    y0 = _np.random.randint(0, max(h - new_h, 0) + 1)
    return fixed_crop(src, x0, y0, new_w, new_h), (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src - mean
    if std is not None:
        src = src / std
    return src


# ------------------------------------------------------------ augmenters
# Reference image.py Augmenter classes (:585-1020) + CreateAugmenter.

class Augmenter:
    """Image augmenter base (reference image.py:585)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _np.random.random() < self.p:
            raw = src._data if isinstance(src, NDArray) else src
            return NDArray(raw[:, ::-1])
        return src


class CastAug(Augmenter):
    def __init__(self, typ='float32'):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = array(mean) if not isinstance(mean, NDArray) else mean
        self.std = array(std) if std is not None and \
            not isinstance(std, NDArray) else std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self.coef = _np.array([[[0.299, 0.587, 0.114]]], 'float32')

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.contrast, self.contrast)
        gray = (src * array(self.coef)).sum() * (3.0 / src.size)
        return src * alpha + gray * (1.0 - alpha)


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self.coef = _np.array([[[0.299, 0.587, 0.114]]], 'float32')

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.saturation, self.saturation)
        gray = (src * array(self.coef)).sum(axis=2, keepdims=True)
        return src * alpha + gray * (1.0 - alpha)


class ColorJitterAug(SequentialAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Reference image.py:CreateAugmenter — the standard augmentation
    pipeline factory."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and len(_np.atleast_1d(mean)):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Legacy image iterator (reference image.py:1285 ImageIter): reads
    from a RecordIO pack (``path_imgrec``) or an image list
    (``path_imglist`` + ``path_root``), decodes host-side, applies the
    augmenter list, yields ``io.DataBatch`` of NCHW data.

    TPU design note: this survives for API parity; the preferred input
    path is ``gluon.data.DataLoader`` (threaded, prefetching into device
    memory) — see mxnet_tpu/io.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root='', shuffle=False,
                 aug_list=None, label_width=1, data_name='data',
                 label_name='softmax_label', last_batch_handle='pad',
                 **kwargs):
        from ..recordio import MXIndexedRecordIO
        assert path_imgrec or path_imglist, \
            'ImageIter needs path_imgrec or path_imglist'
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)
        self._rec = None
        self._imglist = None
        if path_imgrec:
            idx_path = path_imgrec[:-4] + '.idx' \
                if path_imgrec.endswith('.rec') else path_imgrec + '.idx'
            self._rec = MXIndexedRecordIO(idx_path, path_imgrec, 'r')
            self._seq = list(self._rec.keys)
        else:
            self._imglist = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split('\t')
                    labels = [float(v) for v in parts[1:1 + label_width]]
                    self._imglist.append(
                        (labels, path_root + parts[-1]))
            self._seq = list(range(len(self._imglist)))
        self._cur = 0
        self.reset()

    def reset(self):
        self._cur = 0
        if self.shuffle:
            _np.random.shuffle(self._seq)

    def next_sample(self):
        from ..recordio import unpack_img
        if self._cur >= len(self._seq):
            raise StopIteration
        idx = self._seq[self._cur]
        self._cur += 1
        if self._rec is not None:
            header, img = unpack_img(self._rec.read_idx(idx))
            return header.label, img
        label, path = self._imglist[idx]
        return _np.array(label), imread(path)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    # per-sample hooks (overridden by ImageDetIter)
    def _label_shape(self):
        return (self.label_width,)

    def _process_sample(self, img, label):
        """Augment one sample → (image NDArray, label row)."""
        for aug in self.auglist:
            img = aug(img)
        row = _np.atleast_1d(
            label.asnumpy() if isinstance(label, NDArray) else label
        )[:self.label_width]
        return img, row

    def _finalize_labels(self, labels):
        return labels[:, 0] if self.label_width == 1 else labels

    def next(self):
        from ..io import DataBatch
        c, h, w = self.data_shape
        data = _np.zeros((self.batch_size, h, w, c), 'float32')
        labels = _np.full((self.batch_size,) + self._label_shape(), -1.0,
                          'float32')
        i = 0
        pad = 0
        while i < self.batch_size:
            try:
                label, img = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                pad = self.batch_size - i
                break
            if not isinstance(img, NDArray):
                img = array(img)
            img, labels[i] = self._process_sample(img, label)
            data[i] = img.asnumpy()
            i += 1
        batch_data = array(data.transpose(0, 3, 1, 2))   # NCHW
        return DataBatch(data=[batch_data],
                         label=[array(self._finalize_labels(labels))],
                         pad=pad)


# --------------------------------------------------------- detection iter

class DetHorizontalFlipAug(Augmenter):
    """Flip image and x-coordinates of corner-format boxes
    (reference image/detection.py DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _np.random.random() < self.p:
            raw = src._data if isinstance(src, NDArray) else src
            src = NDArray(raw[:, ::-1])
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


class ImageDetIter(ImageIter):
    """Detection iterator (reference image/detection.py ImageDetIter):
    labels are per-object rows ``[cls, x1, y1, x2, y2]`` (normalized
    corners), padded with -1 rows to ``max_objects``. Images resize to
    ``data_shape`` directly (box coords are scale-invariant in normalized
    form); optional box-aware random mirror.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root='', shuffle=False,
                 max_objects=16, rand_mirror=False, mean=None, std=None,
                 **kwargs):
        c, h, w = data_shape
        aug_list = [ForceResizeAug((w, h)), CastAug()]
        if mean is not None or std is not None:
            aug_list.append(ColorNormalizeAug(
                mean if mean is not None else 0.0, std))
        super().__init__(batch_size, data_shape, path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         shuffle=shuffle, aug_list=aug_list,
                         label_width=1, **kwargs)
        self.max_objects = max_objects
        self._det_augs = [DetHorizontalFlipAug(0.5)] if rand_mirror else []

    def _parse_label(self, label):
        """Flat label array → (max_objects, 5), -1-padded (reference
        detection.py _parse_label: header [A, w] prefix supported)."""
        arr = _np.asarray(label, 'float32').ravel()
        if arr.size == 1:               # classification-style scalar
            arr = _np.array([arr[0], 0, 0, 1, 1], 'float32')
        if arr.size % 5 == 2:           # [A, w] header prefix
            arr = arr[2:]
        objs = arr.reshape(-1, 5)[:self.max_objects]
        out = _np.full((self.max_objects, 5), -1.0, 'float32')
        out[:len(objs)] = objs
        return out

    # hooks into the shared ImageIter.next batch loop
    def _label_shape(self):
        return (self.max_objects, 5)

    def _process_sample(self, img, label):
        for aug in self.auglist:
            img = aug(img)
        lab = self._parse_label(label)
        for aug in self._det_augs:
            img, lab = aug(img, lab)
        return img, lab

    def _finalize_labels(self, labels):
        return labels
