"""``mx.image`` — image I/O and augmentation.

Reference: ``python/mxnet/image/image.py`` (ImageIter:1285 + augmenters) and
the C++ decode path (src/io/image_aug_default.cc). Decode runs host-side
(cv2/PIL); augmentation ops run as registered ops so they can execute on
device inside the input pipeline.
"""

import numpy as _np

from ..ndarray.ndarray import NDArray, array


def imread(filename, flag=1, to_rgb=True):
    """Reference image.py:imread."""
    try:
        import cv2
        img = cv2.imread(filename, flag)
        if img is None:
            raise OSError(f'cannot read {filename}')
        if to_rgb and img.ndim == 3:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    except ImportError:
        from PIL import Image
        img = _np.asarray(Image.open(filename).convert(
            'RGB' if flag else 'L'))
    return array(img)


def imdecode(buf, flag=1, to_rgb=True):
    """Reference image.py:imdecode."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    try:
        import cv2
        img = cv2.imdecode(_np.frombuffer(buf, dtype=_np.uint8), flag)
        if to_rgb and img is not None and img.ndim == 3:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    except ImportError:
        import io
        from PIL import Image
        img = _np.asarray(Image.open(io.BytesIO(buf)))
    return array(img)


def imresize(src, w, h, interp=1):
    import jax.image
    raw = src._data if isinstance(src, NDArray) else src
    method = {0: 'nearest', 1: 'linear', 2: 'cubic'}.get(interp, 'linear')
    out = jax.image.resize(raw.astype('float32'), (h, w) + tuple(
        raw.shape[2:]), method)
    return NDArray(out)


def resize_short(src, size, interp=2):
    """Reference image.py:resize_short."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size if isinstance(size, (tuple, list)) else (size, size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    return fixed_crop(src, x0, y0, new_w, new_h), (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size if isinstance(size, (tuple, list)) else (size, size)
    x0 = _np.random.randint(0, max(w - new_w, 0) + 1)
    y0 = _np.random.randint(0, max(h - new_h, 0) + 1)
    return fixed_crop(src, x0, y0, new_w, new_h), (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src - mean
    if std is not None:
        src = src / std
    return src
