"""Core shared definitions: errors, registries, small helpers.

TPU-native analog of the reference's ``python/mxnet/base.py``. That module's
main job — loading ``libmxnet.so`` over ctypes (base.py:276) and generating op
modules from the C registry (base.py:600) — disappears: ops live in a Python
registry (:mod:`mxnet_tpu.ops.registry`) and dispatch straight to jax.numpy /
lax / Pallas. What remains here is the error hierarchy and registry plumbing
shared by the frontend namespaces.
"""

import numpy as _np

string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)


class MXNetError(RuntimeError):
    """Base error type for the framework (reference: python/mxnet/error.py)."""


class NotImplementedForSymbol(MXNetError):
    def __init__(self, function, alias, *args):
        super().__init__()
        self.function = function
        self.alias = alias
        self.args = [str(type(a)) for a in args]

    def __str__(self):
        msg = f'Function {self.function.__name__}'
        if self.alias:
            msg += f' (namely operator "{self.alias}")'
        if self.args:
            msg += ' with arguments ({})'.format(', '.join(self.args))
        msg += ' is not supported for Symbol and only available in NDArray.'
        return msg


class _NullType:
    """Placeholder for arguments not supplied (reference base.py `_Null`)."""

    def __repr__(self):
        return '_Null'

    def __bool__(self):
        return False


_Null = _NullType()


def classproperty(func):
    class _ClassPropertyDescriptor:
        def __init__(self, fget):
            self.fget = fget

        def __get__(self, obj, klass=None):
            if klass is None:
                klass = type(obj)
            return self.fget.__get__(obj, klass)()

    if not isinstance(func, (classmethod, staticmethod)):
        func = classmethod(func)
    return _ClassPropertyDescriptor(func)


_registries = {}


def get_registry(cls):
    return dict(_registries.get(cls, {}))


def register(klass):
    """Class-registry decorator factory, mirroring dmlc registry semantics
    (reference: python/mxnet/registry.py). Used by Optimizer, Initializer,
    LRScheduler, KVStore backends, ...
    """
    registry = _registries.setdefault(klass, {})

    def do_register(subclass_or_name):
        def _reg(subclass, name=None):
            if name is None:
                name = subclass.__name__
            registry[name.lower()] = subclass
            return subclass

        if isinstance(subclass_or_name, str):
            return lambda subclass: _reg(subclass, subclass_or_name)
        return _reg(subclass_or_name)

    return do_register


def registry_create(klass, name, *args, **kwargs):
    registry = _registries.get(klass, {})
    if isinstance(name, klass):
        return name
    key = name.lower()
    if key not in registry:
        raise ValueError(
            f'Cannot find registered {klass.__name__} with name {name}. '
            f'Registered: {sorted(registry)}')
    return registry[key](*args, **kwargs)
