"""INT8 post-training quantization (PTQ).

Reference: ``src/operator/quantization/`` — ``quantize_v2.cc`` /
``dequantize.cc`` / ``requantize.cc`` kernels, histogram calibration with
naive/entropy(KL) modes (``calibrate.cc``), and the ``QuantizeGraph`` pass
that rewrites the graph around quantizable nodes
(``quantize_graph_pass.cc:580``). The reference lowers to MKLDNN/cuDNN int8
kernels; the TPU design lowers to XLA int8 ``dot_general``/conv with
``preferred_element_type=int32`` — the MXU's native int8 path — and keeps
layer outputs in float (the reference's ``enable_float_output`` variant), so
only layer *inputs* need calibrated ranges and there is no int8 graph
plumbing between layers.

Scheme: symmetric, per-tensor. scale = max(|min|,|max|) / 127; zero-point 0.
"""

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .gluon.block import HybridBlock
from .gluon.parameter import Parameter
from .ndarray.ndarray import NDArray
from .ops.quantization_ops import (quantize_v2, dequantize, requantize,
                                   range_to_scale)

__all__ = ['quantize_v2', 'dequantize', 'requantize', 'quantize_net',
           'calib_table', 'QuantizedDense', 'QuantizedConv2D']


# ------------------------------------------------------------ calibration
class _HistogramCollector:
    """Per-layer input min/max + histogram (reference calibrate.cc's
    LayerOutputMinMaxCollector / HistogramCollector)."""

    def __init__(self, num_bins=2048):
        self.num_bins = num_bins
        self.min = None
        self.max = None
        self.hist = None
        self.edges = None

    def collect(self, arr):
        a = _np.asarray(arr, dtype=_np.float32).ravel()
        lo, hi = float(a.min()), float(a.max())
        self.min = lo if self.min is None else min(self.min, lo)
        self.max = hi if self.max is None else max(self.max, hi)
        amax = max(abs(self.min), abs(self.max)) or 1.0
        hist, edges = _np.histogram(a, bins=self.num_bins,
                                    range=(-amax, amax))
        if self.hist is None or len(self.hist) != len(hist) or \
                self.edges[-1] != edges[-1]:
            # range grew: rebuild by re-binning the old histogram midpoints
            if self.hist is not None:
                mids = (self.edges[:-1] + self.edges[1:]) / 2
                old, _ = _np.histogram(mids, bins=self.num_bins,
                                       range=(-amax, amax),
                                       weights=self.hist)
                hist = hist + old.astype(hist.dtype)
            self.edges = edges
        else:
            hist = hist + self.hist
        self.hist = hist

    # threshold selection -------------------------------------------------
    def naive(self):
        return self.min, self.max

    def percentile(self, p=99.99):
        total = self.hist.sum()
        target = total * (p / 100.0)
        c = _np.cumsum(self.hist)
        # symmetric: walk outward from the center until p% mass is covered
        center = self.num_bins // 2
        for w in range(1, center + 1):
            covered = c[min(center + w, self.num_bins - 1)] - \
                (c[center - w - 1] if center - w - 1 >= 0 else 0)
            if covered >= target:
                # covered mass extends through the UPPER edge of bin
                # center+w, i.e. edges[center+w+1]
                t = float(self.edges[min(center + w + 1, self.num_bins)])
                return -t, t
        return self.min, self.max

    def entropy(self, num_quantized_bins=255):
        """KL-divergence threshold search (reference calibrate.cc — the
        TensorRT algorithm: pick the clip threshold whose quantized
        distribution diverges least from the clipped reference)."""
        hist = self.hist.astype(_np.float64)
        total = hist.sum()
        if total == 0:
            return self.min, self.max
        p_full = hist / total
        edges = self.edges
        center = self.num_bins // 2
        eps = 1e-10
        best_t, best_kl = max(abs(self.min), abs(self.max)), _np.inf
        # KL is measured against the FULL distribution, with the window's
        # reconstruction saturating clipped mass onto the edge bins — so
        # clipping genuinely costs divergence (a window whose 2w bins
        # quantize losslessly does not get a free KL=0).
        for w in range(center, num_quantized_bins // 2 - 1,
                       -max(center // 64, 1)):
            lo_i, hi_i = center - w, center + w
            window = hist[lo_i:hi_i]
            if window.sum() == 0:
                continue
            factor = len(window) / num_quantized_bins
            recon = _np.zeros_like(window)
            for i in range(num_quantized_bins):
                s = int(i * factor)
                e = max(int((i + 1) * factor), s + 1)
                chunk = window[s:e]
                nz = (chunk > 0).sum()
                if nz:
                    recon[s:e] = _np.where(chunk > 0, chunk.sum() / nz, 0)
            q = _np.zeros_like(hist)
            q[lo_i:hi_i] = recon
            q[lo_i] += hist[:lo_i].sum()     # saturation
            q[hi_i - 1] += hist[hi_i:].sum()
            q = q / q.sum()
            mask = p_full > 0
            kl = float(_np.sum(p_full[mask] * _np.log(
                p_full[mask] / _np.maximum(q[mask], eps))))
            if kl < best_kl:
                best_kl = kl
                best_t = float(edges[hi_i] if hi_i < len(edges) else
                               edges[-1])
        return -best_t, best_t


def calib_table(collectors, mode='entropy'):
    """collectors: {layer_name: _HistogramCollector} → {name: (min, max)}.
    Layers never exercised by the calibration data are omitted.
    Reference: SetCalibTableToQuantizedGraph (quantize_graph_pass.cc)."""
    if mode not in ('naive', 'percentile', 'entropy'):
        raise ValueError(f'unknown calib_mode {mode!r}; expected '
                         "'naive', 'percentile' or 'entropy'")
    table = {}
    for name, c in collectors.items():
        if c.hist is None:
            continue
        if mode == 'naive':
            table[name] = c.naive()
        elif mode == 'percentile':
            table[name] = c.percentile()
        else:
            table[name] = c.entropy()
    return table


# ------------------------------------------------------- quantized layers
class _QuantizedLayer(HybridBlock):
    """Shared int8 state: quantized weight + scales + input calib range.

    The dequantize lives in the matmul epilogue (ops/quantization_ops.py
    ``quantized_dense`` / ``quantized_conv2d``): int32 accumulator →
    per-channel scale → bias → activation-dtype downcast inside one
    fused kernel/region, so the historical ``unfused-dequant``
    suppression this class carried is gone — the lint passes by
    construction (docs/kernels.md)."""

    def __init__(self, float_layer, in_min, in_max,
                 activation_dtype='bfloat16', **kwargs):
        super().__init__(**kwargs)
        # inter-layer activations leave in this dtype: bf16 halves the
        # HBM bytes between layers vs f32 — on a bandwidth-bound device
        # an f32-activation int8 net is SLOWER than the bf16 float net
        # (r4 roofline analysis, docs/perf_resnet.md); the int32->float
        # rescale still happens in f32 before the downcast
        self._act_dtype = jnp.dtype(activation_dtype)
        w = float_layer.weight.data()._data.astype(jnp.float32)
        # per-output-channel symmetric scales (axis 0 is out-channels
        # for both Dense (O, I) and Conv OIHW): finer than the old
        # per-tensor scale, and free now that the scale multiply rides
        # the matmul epilogue as a (O,) vector instead of a scalar
        red = tuple(range(1, w.ndim))
        amax = jnp.max(jnp.abs(w), axis=red) if red else jnp.abs(w)
        self._w_scale = jnp.where(amax > 0, amax / 127.0,
                                  1.0).astype(jnp.float32)      # (O,)
        cshape = (-1,) + (1,) * (w.ndim - 1)
        qw = jnp.clip(jnp.round(w / self._w_scale.reshape(cshape)),
                      -127, 127).astype(jnp.int8)
        qw = _np.asarray(qw, dtype=_np.int8)
        self.qweight = Parameter('qweight', shape=qw.shape, dtype='int8',
                                 grad_req='null')
        self.qweight.initialize(init='zeros')
        self.qweight.set_data(NDArray(jnp.asarray(qw)))
        self._has_bias = getattr(float_layer, 'bias', None) is not None and \
            getattr(float_layer, '_use_bias', True)
        if self._has_bias:
            self.bias = Parameter('bias', shape=float_layer.bias.shape,
                                  grad_req='null')
            self.bias.initialize(init='zeros')
            self.bias.set_data(float_layer.bias.data())
        self._x_scale = float(range_to_scale(in_min, in_max))
        self.collected_range = (in_min, in_max)

    def _quantize_input(self, x):
        xr = x._data if isinstance(x, NDArray) else x
        q, _, _ = quantize_v2(xr.astype(jnp.float32), *self.collected_range)
        return q


class QuantizedDense(_QuantizedLayer):
    """int8 FullyConnected (reference quantized_fully_connected.cc):
    int8 × int8 → int32 on the MXU, one float rescale out."""

    def __init__(self, float_layer, in_min, in_max, **kwargs):
        super().__init__(float_layer, in_min, in_max, **kwargs)
        self._flatten = float_layer._flatten
        self.act = float_layer.act

    def forward(self, x):
        from .ops.quantization_ops import quantized_dense
        q = self._quantize_input(x)
        if self._flatten and q.ndim > 2:
            q = q.reshape(q.shape[0], -1)
        qw = self.qweight.data()._data
        out = quantized_dense(
            q, qw, self._x_scale * self._w_scale,
            self.bias.data()._data if self._has_bias else None,
            out_dtype=self._act_dtype)
        out = NDArray(out)
        if self.act is not None:
            out = self.act(out)
        return out


class QuantizedConv2D(_QuantizedLayer):
    """int8 Convolution (reference quantized_conv.cc)."""

    def __init__(self, float_layer, in_min, in_max, **kwargs):
        super().__init__(float_layer, in_min, in_max, **kwargs)
        self._stride = float_layer._strides
        self._pad = float_layer._padding
        self._dilate = float_layer._dilation
        self._groups = float_layer._groups
        self._layout = float_layer._layout or 'NCHW'
        self.act = float_layer.act

    def forward(self, x):
        from .ops.quantization_ops import quantized_conv2d
        q = self._quantize_input(x)
        qw = self.qweight.data()._data
        stride = self._stride if isinstance(self._stride, tuple) else \
            (self._stride,) * 2
        pad = self._pad if isinstance(self._pad, tuple) else (self._pad,) * 2
        dil = self._dilate if isinstance(self._dilate, tuple) else \
            (self._dilate,) * 2
        out = quantized_conv2d(
            q, qw, self._x_scale * self._w_scale,
            self.bias.data()._data if self._has_bias else None,
            out_dtype=self._act_dtype, strides=stride, padding=pad,
            dilation=dil, groups=self._groups, layout=self._layout)
        out = NDArray(out)
        if self.act is not None:
            out = self.act(out)
        return out


# --------------------------------------------------------- graph rewrite
def _quantizable(block):
    from .gluon.nn.basic_layers import Dense
    from .gluon.nn.conv_layers import Conv2D
    if isinstance(block, Dense):
        return QuantizedDense
    if isinstance(block, Conv2D):
        return QuantizedConv2D
    return None


def _walk(block, prefix=''):
    for name, child in list(block._children.items()):
        path = f'{prefix}{name}'
        yield block, name, path, child
        yield from _walk(child, path + '.')


def quantize_net(net, calib_data=None, calib_mode='entropy',
                 quantized_dtype='int8', exclude_layers=None,
                 num_calib_batches=None, logger=None,
                 activation_dtype='bfloat16'):
    """Quantize a trained network for int8 inference.

    The reference flow (quantize_graph_pass.cc + calibrate.cc): insert
    quantize/dequantize around quantizable nodes, run calibration batches,
    set the calib table. Here: run ``calib_data`` through the float net with
    input-collecting hooks, derive per-layer ranges by ``calib_mode``
    ('naive' | 'percentile' | 'entropy'), then swap each quantizable child
    (Dense/Conv2D) for its int8 twin. Children are swapped in place; if the
    net ITSELF is a quantizable layer its int8 twin is the return value —
    always use the returned block. Hybridization is cleared (compiled caches
    would keep serving the float graph); re-hybridize afterwards.
    """
    assert quantized_dtype == 'int8', 'TPU MXU int8 path only'
    if calib_data is None:
        raise ValueError('calib_data is required for post-training '
                         'quantization')
    exclude_layers = set(exclude_layers or ())

    # Compiled caches bypass child hooks and would keep executing the float
    # graph after the swap — calibrate and rewrite in eager mode. The caller
    # re-hybridizes the quantized net afterwards.
    if isinstance(net, HybridBlock) or hasattr(net, 'hybridize'):
        net.hybridize(False)

    root_cls = _quantizable(net)
    targets = [(parent, name, path, child)
               for parent, name, path, child in _walk(net)
               if _quantizable(child) and path not in exclude_layers]
    if root_cls is not None and '.' not in exclude_layers:
        targets.append((None, None, '.', net))  # the net IS the layer
    if not targets:
        return net

    collectors = {path: _HistogramCollector()
                  for _, _, path, _ in targets}
    handles = []

    def make_hook(path):
        def hook(block, inputs):
            x = inputs[0]
            collectors[path].collect(
                x.asnumpy() if isinstance(x, NDArray) else x)
        return hook

    try:
        for _, _, path, child in targets:
            hook = make_hook(path)
            child._forward_pre_hooks.append(hook)
            handles.append((child, hook))
        n = 0
        for batch in calib_data:
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            net(x if isinstance(x, NDArray) else NDArray(jnp.asarray(x)))
            n += 1
            if num_calib_batches is not None and n >= num_calib_batches:
                break
    finally:
        for child, hook in handles:
            child._forward_pre_hooks.remove(hook)

    table = calib_table(collectors, calib_mode)
    result = net
    for parent, name, path, child in targets:
        if path not in table:
            # layer never saw calibration data (e.g. a disabled branch):
            # leave it in float
            if logger:
                logger.warning('layer %s not exercised by calib_data; '
                               'kept in float', path)
            continue
        lo, hi = table[path]
        qlayer = _quantizable(child)(child, lo, hi,
                                     activation_dtype=activation_dtype)
        if parent is None:
            result = qlayer  # root swap happens via the return value
            continue
        parent._children[name] = qlayer
        # attribute access must resolve to the new child too
        for attr, value in list(parent.__dict__.items()):
            if value is child:
                parent.__dict__[attr] = qlayer
    if logger:
        for path, (lo, hi) in table.items():
            logger.info('calibrated %s: [%.5f, %.5f]', path, lo, hi)
    return result
