"""Shared test harness (reference python/mxnet/test_utils.py, 2,604 LoC).

Ported first per SURVEY §7 P0 — all suite tests depend on it:
``default_context`` (:57), ``assert_almost_equal`` with dtype-aware
tolerances via ``get_tols`` (:650, :74-168), ``check_numeric_gradient``
(finite differences vs autograd with per-dtype eps, :1040,
``default_numeric_eps`` :100), ``rand_ndarray``/``rand_sparse_ndarray``
with the density/stype/distribution matrix (:391-520).

TPU twist on the reference: ``bfloat16`` is a first-class tolerance
class (the MXU's native dtype — 8 mantissa bits, LOOSER than fp16's
10), and ``effective_dtype`` maps f32 data to the bf16 tolerance class
when ``MXNET_TPU_F32_VIA_MXU=1`` declares that the values flowed
through bf16-input matmul/conv (the TPU analog of the reference's
TF32-on-arch-80 demotion, test_utils.py:108-132).
"""

import functools
import os

import numpy as _np

from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array

_DEFAULT_CTX = None


def _bf16_dtype():
    import ml_dtypes
    return _np.dtype(ml_dtypes.bfloat16)


_INT_EXACT = (bool, _np.int8, _np.uint8, _np.int16, _np.uint16,
              _np.int32, _np.uint32, _np.int64, _np.uint64)


@functools.lru_cache(maxsize=1)
def default_rtols():
    """Per-dtype relative tolerances (reference test_utils.py:74),
    extended with bfloat16 (8 mantissa bits -> ulp 2^-8 at 1.0).
    Cached: assert_almost_equal sits on hot comparison paths. Treat the
    returned dict as read-only."""
    tols = {_np.dtype(_np.float16): 1e-2,
            _np.dtype(_np.float32): 1e-4,
            _np.dtype(_np.float64): 1e-5,
            _bf16_dtype(): 2e-2}
    tols.update({_np.dtype(t): 0 for t in _INT_EXACT})
    return tols


@functools.lru_cache(maxsize=1)
def default_atols():
    """Per-dtype absolute tolerances (reference test_utils.py:87)."""
    tols = {_np.dtype(_np.float16): 1e-3,
            _np.dtype(_np.float32): 1e-5,
            _np.dtype(_np.float64): 1e-8,
            _bf16_dtype(): 1e-2}
    tols.update({_np.dtype(t): 0 for t in _INT_EXACT})
    return tols


@functools.lru_cache(maxsize=1)
def default_numeric_eps():
    """Finite-difference eps per dtype (reference test_utils.py:100 —
    powers of two so the input delta drops no mantissa bits)."""
    return {_np.dtype(_np.float16): 1.0 / 2 ** 6,
            _bf16_dtype(): 1.0 / 2 ** 5,
            _np.dtype(_np.float32): 1.0 / 2 ** 9,
            _np.dtype(_np.float64): 1.0 / 2 ** 14}


def effective_dtype(dat):
    """The dtype whose tolerance class governs comparisons of ``dat``
    (reference test_utils.py:108). On TPU the MXU computes f32-io
    matmuls/convs from bf16 inputs unless the op requested higher
    precision; set ``MXNET_TPU_F32_VIA_MXU=1`` in tests whose f32
    outputs flowed through such ops to compare at bf16 precision."""
    dtype = _np.dtype(dat.dtype) if hasattr(dat, 'dtype') \
        else _np.dtype(type(dat))
    if dtype == _np.dtype(_np.float32) \
            and os.environ.get('MXNET_TPU_F32_VIA_MXU') == '1':
        return _bf16_dtype()
    return dtype


def get_tolerance(dat, tol, default_tols, fallback=1e-4):
    """Reference test_utils.py:135 — explicit tol wins; else the
    default for dat's effective dtype."""
    if tol is not None:
        return tol
    return default_tols.get(effective_dtype(dat), fallback)


def get_tols(x, y, rtol=None, atol=None):
    """Tolerances for comparing two datasets: the LOOSEST of the two
    operands' per-dtype defaults (reference test_utils.py:154)."""
    if not hasattr(x, 'dtype'):
        x = _np.asarray(x)
    if not hasattr(y, 'dtype'):
        y = _np.asarray(y)
    rtol = max(get_tolerance(x, rtol, default_rtols()),
               get_tolerance(y, rtol, default_rtols()))
    atol = max(get_tolerance(x, atol, default_atols(), fallback=1e-5),
               get_tolerance(y, atol, default_atols(), fallback=1e-5))
    return rtol, atol


def get_rtol(rtol=None, dtype=None):
    """Reference test_utils.py:175."""
    if rtol is not None:
        return rtol
    return default_rtols()[_np.dtype(dtype or _np.float64)]


def get_atol(atol=None, dtype=None):
    """Reference test_utils.py:171."""
    if atol is not None:
        return atol
    return default_atols()[_np.dtype(dtype or _np.float64)]


def default_context():
    """Reference test_utils.py:57 — switches the whole suite CPU↔TPU via
    MXNET_TEST_DEVICE."""
    global _DEFAULT_CTX
    if _DEFAULT_CTX is None:
        dev = os.environ.get('MXNET_TEST_DEVICE', '')
        _DEFAULT_CTX = Context(dev) if dev else current_context()
    return _DEFAULT_CTX


def set_default_context(ctx):
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def default_dtype():
    return _np.float32


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def find_max_violation(a, b, rtol, atol):
    """Location + size of the worst tolerance violation (reference
    test_utils.py:578 _find_max_violation)."""
    absdiff = _np.where(_np.equal(a, b), 0, _np.abs(a - b))
    tol = atol + rtol * _np.abs(b)
    violation = absdiff / (tol + 1e-20)
    loc = _np.argmax(violation)
    idx = _np.unravel_index(loc, violation.shape) if violation.shape \
        else ()
    return idx, float(_np.max(violation))


def assert_almost_equal(a, b, rtol=None, atol=None, names=('a', 'b'),
                        equal_nan=False, use_broadcast=True):
    """Reference test_utils.py:650 — tolerances from get_tols (the
    loosest of both operands' dtype classes), max-violation location in
    the failure message."""
    a_nd, b_nd = a, b
    a, b = _as_np(a), _as_np(b)
    rtol, atol = get_tols(a_nd if hasattr(a_nd, 'dtype') else a,
                          b_nd if hasattr(b_nd, 'dtype') else b,
                          rtol, atol)
    if not use_broadcast:
        assert a.shape == b.shape, f'shape mismatch {a.shape} vs {b.shape}'
    if a.dtype == bool and b.dtype == bool:
        _np.testing.assert_equal(a, b)
        return
    af = a.astype(_np.float64) if a.dtype != bool else a
    bf = b.astype(_np.float64) if b.dtype != bool else b
    try:
        if _np.allclose(af, bf, rtol=rtol, atol=atol,
                        equal_nan=equal_nan):
            return
        ab, bb = _np.broadcast_arrays(af, bf)
    except ValueError:
        # non-broadcastable shapes are a comparison FAILURE, not a
        # harness error: keep raising AssertionError like the
        # pre-fast-path implementation did
        raise AssertionError(
            f'{names[0]} != {names[1]}: shapes {a.shape} and {b.shape} '
            f'cannot be broadcast together') from None
    idx, viol = find_max_violation(ab, bb, rtol, atol)
    _np.testing.assert_allclose(
        af, bf, rtol=rtol, atol=atol, equal_nan=equal_nan,
        err_msg=(f'{names[0]} != {names[1]} (rtol={rtol}, atol={atol}): '
                 f'worst violation {viol:.2f}x tolerance at {idx}: '
                 f'{names[0]}={ab[idx]!r} {names[1]}={bb[idx]!r}'))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False,
                 use_broadcast=True):
    a_nd, b_nd = a, b
    a, b = _as_np(a), _as_np(b)
    if not use_broadcast and a.shape != b.shape:
        return False
    rtol, atol = get_tols(a_nd if hasattr(a_nd, 'dtype') else a,
                          b_nd if hasattr(b_nd, 'dtype') else b,
                          rtol, atol)
    return _np.allclose(a.astype(_np.float64), b.astype(_np.float64),
                        rtol=rtol, atol=atol, equal_nan=equal_nan)


def same(a, b):
    return _np.array_equal(_as_np(a), _as_np(b))


def assign_each(the_input, function):
    """Element-wise value rewrite (reference test_utils.py:66)."""
    if function is None:
        return the_input
    return _np.vectorize(function)(the_input).astype(the_input.dtype)


def _get_uniform_dataset_csr(num_rows, num_cols, density, dtype,
                             data_init=None, shuffle_csr_indices=False):
    """Uniformly-distributed CSR (reference test_utils.py:262): every
    element independently present with probability ``density``."""
    mask = _np.random.rand(num_rows, num_cols) < density
    dense = _np.where(mask, _np.random.rand(num_rows, num_cols), 0.0)
    if data_init is not None:
        dense = _np.where(mask, data_init, 0.0)
    dense = dense.astype(dtype)
    from .ndarray import sparse as _sp
    csr = _sp.csr_matrix(array(dense))
    if shuffle_csr_indices:
        # permute the within-row order of (indices, data) pairs: the
        # reference uses this to prove kernels do not assume sorted
        # column indices within a row
        indptr = csr.indptr.asnumpy()
        indices = csr.indices.asnumpy().copy()
        data = csr.data.asnumpy().copy()
        for r in range(num_rows):
            s, e = int(indptr[r]), int(indptr[r + 1])
            perm = _np.random.permutation(e - s)
            indices[s:e] = indices[s:e][perm]
            data[s:e] = data[s:e][perm]
        csr = _sp.CSRNDArray(array(data), array(indptr),
                             array(indices), (num_rows, num_cols))
    return csr


def _get_powerlaw_dataset_csr(num_rows, num_cols, density, dtype):
    """Power-law CSR (reference test_utils.py:300): row n+1 holds twice
    row n's nnz until the density budget is spent — the classic
    recommender-workload shape."""
    total_nnz = int(num_rows * num_cols * density)
    unused = total_nnz
    dense = _np.zeros((num_rows, num_cols), dtype=dtype)
    col_max = 2
    for r in range(num_rows):
        if unused <= 0:
            break
        n = min(col_max, num_cols, unused)
        cols = _np.random.choice(num_cols, size=n, replace=False)
        dense[r, cols] = _np.random.rand(n)
        unused -= n
        col_max *= 2
    from .ndarray import sparse as _sp
    return _sp.csr_matrix(array(dense))


def rand_sparse_ndarray(shape, stype, density=None, dtype=None,
                        distribution=None, data_init=None,
                        rsp_indices=None, modifier_func=None,
                        shuffle_csr_indices=False, ctx=None):
    """Random sparse ndarray + its host-side pieces (reference
    test_utils.py:391-479): ``row_sparse`` samples present rows with
    probability ``density`` (or takes explicit ``rsp_indices``); CSR
    supports the uniform and powerlaw distributions. Returns
    ``(ndarray, (values, indices))`` for row_sparse and
    ``(ndarray, (indptr, indices, data))`` for csr. ``ctx`` is
    accepted for reference-signature parity; arrays land on the
    default context (single-process placement is a jit concern on this
    backend, not an allocation-time one)."""
    from .ndarray import sparse as _sp

    density = _np.random.rand() if density is None else density
    dtype = _np.dtype(dtype or default_dtype())
    distribution = distribution or 'uniform'
    if stype == 'row_sparse':
        assert distribution == 'uniform', \
            f'distribution {distribution} not supported for row_sparse'
        if rsp_indices is not None:
            indices = _np.asarray(rsp_indices)
            assert len(indices) <= shape[0]
            indices = _np.sort(indices)
        else:
            indices = _np.argwhere(
                _np.random.rand(shape[0]) < density).flatten()
        if indices.shape[0] == 0:
            result = _sp.zeros('row_sparse', shape, dtype=str(dtype))
            return result, (_np.zeros((0,) + tuple(shape[1:]), dtype),
                            _np.array([], dtype=_np.int64))
        val = _np.random.rand(indices.shape[0], *shape[1:]).astype(dtype)
        if data_init is not None:
            val.fill(data_init)
        if modifier_func is not None:
            val = assign_each(val, modifier_func)
        arr = _sp.row_sparse_array(
            (array(val), array(indices.astype(_np.int64))), shape=shape)
        return arr, (val, indices)
    if stype == 'csr':
        assert len(shape) == 2
        if distribution == 'uniform':
            csr = _get_uniform_dataset_csr(
                shape[0], shape[1], density, dtype, data_init=data_init,
                shuffle_csr_indices=shuffle_csr_indices)
        elif distribution == 'powerlaw':
            csr = _get_powerlaw_dataset_csr(shape[0], shape[1], density,
                                            dtype)
        else:
            raise ValueError(f'distribution not supported: {distribution}')
        if modifier_func is not None:
            # rewrite the stored nonzeros only (the reference applies
            # modifier_func through create_sparse_array the same way)
            data = assign_each(csr.data.asnumpy(), modifier_func)
            csr = _sp.CSRNDArray(array(data), csr.indptr, csr.indices,
                                 tuple(shape))
        return csr, (csr.indptr, csr.indices, csr.data)
    raise ValueError(f'unknown storage type {stype!r}')


def create_sparse_array(shape, stype, data_init=None, rsp_indices=None,
                        dtype=None, modifier_func=None, density=.5,
                        shuffle_csr_indices=False):
    """Reference test_utils.py:498 — canonical-format sparse array."""
    arr, _ = rand_sparse_ndarray(
        shape, stype, density=density, dtype=dtype, data_init=data_init,
        rsp_indices=rsp_indices, modifier_func=modifier_func,
        shuffle_csr_indices=shuffle_csr_indices)
    return arr


def create_sparse_array_zd(shape, stype, density, data_init=None,
                           rsp_indices=None, dtype=None,
                           modifier_func=None, shuffle_csr_indices=False):
    """Reference test_utils.py:523 — rsp density comes only from the
    explicit index list."""
    if stype == 'row_sparse':
        density = 0.0
        if rsp_indices is not None:
            assert len(rsp_indices) <= shape[0]
    return create_sparse_array(shape, stype, data_init=data_init,
                               rsp_indices=rsp_indices, dtype=dtype,
                               modifier_func=modifier_func,
                               density=density,
                               shuffle_csr_indices=shuffle_csr_indices)


def rand_ndarray(shape, stype='default', density=None, dtype='float32',
                 ctx=None, scale=1.0, modifier_func=None,
                 shuffle_csr_indices=False, distribution=None):
    """Reference test_utils.py:482: dense, or any sparse stype via
    rand_sparse_ndarray's density/distribution matrix. ``scale``
    multiplies the sparse values too (base generation is [0, 1))."""
    if stype != 'default':
        if scale != 1.0:
            base = modifier_func
            modifier_func = (lambda v: v * scale) if base is None \
                else (lambda v: base(v) * scale)
        arr, _ = rand_sparse_ndarray(
            shape, stype, density=density, dtype=dtype,
            modifier_func=modifier_func,
            shuffle_csr_indices=shuffle_csr_indices,
            distribution=distribution, ctx=ctx)
        return arr
    dtype = _np.dtype(dtype)
    if dtype.kind == 'f':
        data = _np.random.uniform(-scale, scale, shape).astype(dtype)
    else:
        data = _np.random.randint(-64, 64, shape).astype(dtype)
    return array(data, ctx=ctx or default_context(), dtype=dtype)


def rand_shape_nd(ndim, dim=10, allow_zero_size=False):
    low = 0 if allow_zero_size else 1
    return tuple(_np.random.randint(low, dim + 1, size=ndim))


def rand_shape_2d(dim0=10, dim1=10):
    return rand_shape_nd(2, max(dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return rand_shape_nd(3, max(dim0, dim1, dim2))


def random_arrays(*shapes):
    arrays = [_np.random.randn(*s).astype(_np.float32) if s else
              _np.float32(_np.random.randn()) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def check_numeric_gradient(fn, inputs, eps=None, rtol=1e-2, atol=1e-3):
    """Finite differences vs autograd (reference test_utils.py:1040).

    ``fn`` maps a list of NDArrays to a scalar-reducible NDArray. Checks
    d(sum(fn))/d(input) against central differences. ``eps`` defaults
    per input dtype from :func:`default_numeric_eps` (power-of-two
    deltas drop no mantissa bits — reference :100).
    """
    from . import autograd

    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    if eps is None:
        eps = max(default_numeric_eps().get(
            _np.dtype(x.dtype), 1.0 / 2 ** 9) for x in inputs)
        # the central-difference probe itself runs in float32 below, so
        # never probe finer than the f32-appropriate delta
        eps = float(max(eps, 1.0 / 2 ** 9))
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
        loss = out.sum()
    loss.backward()
    analytic = [x.grad.asnumpy() for x in inputs]

    for i, x in enumerate(inputs):
        host = x.asnumpy().astype(_np.float64)
        num = _np.zeros_like(host)
        it = _np.nditer(host, flags=['multi_index'])
        while not it.finished:
            idx = it.multi_index
            orig = host[idx]
            host[idx] = orig + eps
            fp = fn(*[array(host.astype(_np.float32)) if j == i else inputs[j]
                      for j in range(len(inputs))]).sum().asnumpy()
            host[idx] = orig - eps
            fm = fn(*[array(host.astype(_np.float32)) if j == i else inputs[j]
                      for j in range(len(inputs))]).sum().asnumpy()
            host[idx] = orig
            num[idx] = (fp - fm) / (2 * eps)
            it.iternext()
        _np.testing.assert_allclose(analytic[i], num, rtol=rtol, atol=atol,
                                    err_msg=f'gradient mismatch for input {i}')


def check_consistency(fn, inputs, ctx_list=None, *, dtype_list=None,
                      rtol=None, atol=None):
    """Same computation across contexts AND dtypes (reference
    test_utils.py check_consistency: each spec in ctx_list carried its
    own type_dict; every run is compared against the highest-precision
    run at the LOOSER operand's tolerance class).

    ``fn`` maps NDArrays to an NDArray (or tuple). ``dtype_list``
    defaults to ``['float32']``; pass e.g. ``['float16', 'bfloat16',
    'float32']`` to sweep the matrix — the float32 run is the
    reference, and each lower-precision run must agree within ITS
    dtype-class tolerance (get_tols). Returns the per-(ctx, dtype)
    outputs keyed ``(ctx, dtype)`` for further assertions."""
    ctx_list = ctx_list or [cpu(), default_context()]
    uniq, seen = [], set()
    for c in ctx_list:                 # cpu CI: default ctx == cpu(0)
        if str(c) not in seen:
            seen.add(str(c))
            uniq.append(c)
    ctx_list = uniq
    dtype_list = list(dtype_list or ['float32'])
    # highest-precision dtype is the reference run. bf16 ranks BELOW
    # fp16: 8 mantissa bits vs 10 (same ordering as the tolerance
    # classes above). Normalize via np.dtype(...).name so scalar types
    # (np.float16) and strings rank identically.
    order = {'float64': 3, 'float32': 2, 'float16': 1, 'bfloat16': 0}

    def _name(d):
        return _np.dtype(d).name

    def _floatish(dtype):
        return _np.dtype(dtype).kind == 'f' or \
            _np.dtype(dtype) == _bf16_dtype()

    ref_dt = max(dtype_list, key=lambda d: order.get(_name(d), 2))
    results = {}
    for ctx in ctx_list:
        for dt in dtype_list:
            xs = [x.as_in_context(ctx).astype(dt)
                  if _floatish(x.dtype) else x.as_in_context(ctx)
                  for x in inputs]
            out = fn(*xs)
            outs = out if isinstance(out, (list, tuple)) else (out,)
            results[(str(ctx), _name(dt))] = [_as_np(o) for o in outs]
    ref_key = (str(ctx_list[0]), _name(ref_dt))
    ref = results[ref_key]
    for key, outs in results.items():
        if key == ref_key:
            continue
        assert len(outs) == len(ref), (
            f'{key} returned {len(outs)} outputs but the reference '
            f'{ref_key} returned {len(ref)}')
        for i, (got, want) in enumerate(zip(outs, ref)):
            assert_almost_equal(
                got, want, rtol=rtol, atol=atol,
                names=(f'{key}[{i}]', f'{ref_key}[{i}]'))
    return results


def simple_forward(fn, *inputs):
    out = fn(*[array(x) if not isinstance(x, NDArray) else x
               for x in inputs])
    return out.asnumpy() if isinstance(out, NDArray) else \
        tuple(o.asnumpy() for o in out)


def discard_stderr(*a, **kw):
    import contextlib
    import io
    return contextlib.redirect_stderr(io.StringIO())


class DummyIter:
    pass


def list_gpus():
    from .context import num_gpus
    return list(range(num_gpus()))


def environment(*args):
    """with_environment ctx manager (reference tests common.py:313)."""
    import contextlib
    import os as _os

    @contextlib.contextmanager
    def ctx():
        key, value = args
        old = _os.environ.get(key)
        if value is None:
            _os.environ.pop(key, None)
        else:
            _os.environ[key] = str(value)
        try:
            yield
        finally:
            if old is None:
                _os.environ.pop(key, None)
            else:
                _os.environ[key] = old
    return ctx()


def check_symbolic_forward(sym, inputs, expected, rtol=1e-4, atol=1e-5,
                           ctx=None):
    """Reference test_utils.py:1190 — bind inputs, compare outputs."""
    args = sym.list_arguments()
    if isinstance(inputs, (list, tuple)):
        bindings = dict(zip(args, inputs))
    else:
        bindings = dict(inputs)
    bindings = {k: v if isinstance(v, NDArray) else array(v, ctx=ctx)
                for k, v in bindings.items()}
    outs = sym.eval(**bindings)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    assert len(outs) == len(expected), \
        f'{len(outs)} outputs vs {len(expected)} expected'
    for got, want in zip(outs, expected):
        assert_almost_equal(got, want, rtol=rtol, atol=atol)
    return outs


def check_symbolic_backward(sym, inputs, out_grads, expected_grads,
                            rtol=1e-2, atol=1e-4, ctx=None):
    """Reference test_utils.py check_symbolic_backward — grads of a bound
    symbol w.r.t. its arguments against expected values."""
    from . import autograd

    args = sym.list_arguments()
    if isinstance(inputs, (list, tuple)):
        inputs = dict(zip(args, inputs))
    nd_in = {k: v if isinstance(v, NDArray) else array(v, ctx=ctx)
             for k, v in inputs.items()}
    for v in nd_in.values():
        v.attach_grad()
    with autograd.record():
        outs = sym.eval(**nd_in)
        if not isinstance(out_grads, (list, tuple)):
            out_grads = [out_grads]
    heads = list(outs)
    grads = [g if isinstance(g, NDArray) else array(g, ctx=ctx)
             for g in out_grads]
    from . import _tape
    _tape.backward(heads, grads)
    if isinstance(expected_grads, (list, tuple)):
        expected_grads = dict(zip(args, expected_grads))
    result = {}
    for name, want in expected_grads.items():
        got = nd_in[name].grad
        if want is not None:
            assert_almost_equal(got, want, rtol=rtol, atol=atol)
        result[name] = got
    return result
