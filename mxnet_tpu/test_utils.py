"""Shared test harness (reference python/mxnet/test_utils.py, 2,604 LoC).

Ported first per SURVEY §7 P0 — all suite tests depend on it:
``default_context`` (:57), ``assert_almost_equal`` with dtype-aware
tolerances (:650), ``check_numeric_gradient`` (finite differences vs
autograd, :1040), ``rand_ndarray`` (:391).
"""

import os

import numpy as _np

from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array

_DEFAULT_CTX = None

_DEFAULT_RTOL = {
    _np.dtype(_np.float16): 1e-2,
    _np.dtype(_np.float32): 1e-4,
    _np.dtype(_np.float64): 1e-5,
    _np.dtype(_np.int32): 0,
    _np.dtype(_np.int64): 0,
}
_DEFAULT_ATOL = {
    _np.dtype(_np.float16): 1e-3,
    _np.dtype(_np.float32): 1e-5,
    _np.dtype(_np.float64): 1e-8,
    _np.dtype(_np.int32): 0,
    _np.dtype(_np.int64): 0,
}


def default_context():
    """Reference test_utils.py:57 — switches the whole suite CPU↔TPU via
    MXNET_TEST_DEVICE."""
    global _DEFAULT_CTX
    if _DEFAULT_CTX is None:
        dev = os.environ.get('MXNET_TEST_DEVICE', '')
        _DEFAULT_CTX = Context(dev) if dev else current_context()
    return _DEFAULT_CTX


def set_default_context(ctx):
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def default_dtype():
    return _np.float32


def _tols(a, b, rtol, atol):
    dt = _np.result_type(a.dtype, b.dtype)
    if rtol is None:
        rtol = _DEFAULT_RTOL.get(_np.dtype(dt), 1e-4)
    if atol is None:
        atol = _DEFAULT_ATOL.get(_np.dtype(dt), 1e-5)
    return rtol, atol


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def assert_almost_equal(a, b, rtol=None, atol=None, names=('a', 'b'),
                        equal_nan=False, use_broadcast=True):
    """Reference test_utils.py:650."""
    a, b = _as_np(a), _as_np(b)
    rtol, atol = _tols(a, b, rtol, atol)
    if not use_broadcast:
        assert a.shape == b.shape, f'shape mismatch {a.shape} vs {b.shape}'
    _np.testing.assert_allclose(a.astype(_np.float64) if a.dtype != bool else a,
                                b.astype(_np.float64) if b.dtype != bool else b,
                                rtol=rtol, atol=atol, equal_nan=equal_nan,
                                err_msg=f'{names[0]} != {names[1]}')


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a, b = _as_np(a), _as_np(b)
    rtol, atol = _tols(a, b, rtol, atol)
    return _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def same(a, b):
    return _np.array_equal(_as_np(a), _as_np(b))


def rand_ndarray(shape, stype='default', density=None, dtype='float32',
                 ctx=None, scale=1.0):
    """Reference test_utils.py:391 (dense; sparse stypes arrive with the
    sparse module)."""
    if stype != 'default':
        raise NotImplementedError('sparse rand_ndarray later')
    dtype = _np.dtype(dtype)
    if dtype.kind == 'f':
        data = _np.random.uniform(-scale, scale, shape).astype(dtype)
    else:
        data = _np.random.randint(-64, 64, shape).astype(dtype)
    return array(data, ctx=ctx or default_context(), dtype=dtype)


def rand_shape_nd(ndim, dim=10, allow_zero_size=False):
    low = 0 if allow_zero_size else 1
    return tuple(_np.random.randint(low, dim + 1, size=ndim))


def rand_shape_2d(dim0=10, dim1=10):
    return rand_shape_nd(2, max(dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return rand_shape_nd(3, max(dim0, dim1, dim2))


def random_arrays(*shapes):
    arrays = [_np.random.randn(*s).astype(_np.float32) if s else
              _np.float32(_np.random.randn()) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite differences vs autograd (reference test_utils.py:1040).

    ``fn`` maps a list of NDArrays to a scalar-reducible NDArray. Checks
    d(sum(fn))/d(input) against central differences.
    """
    from . import autograd

    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
        loss = out.sum()
    loss.backward()
    analytic = [x.grad.asnumpy() for x in inputs]

    for i, x in enumerate(inputs):
        host = x.asnumpy().astype(_np.float64)
        num = _np.zeros_like(host)
        it = _np.nditer(host, flags=['multi_index'])
        while not it.finished:
            idx = it.multi_index
            orig = host[idx]
            host[idx] = orig + eps
            fp = fn(*[array(host.astype(_np.float32)) if j == i else inputs[j]
                      for j in range(len(inputs))]).sum().asnumpy()
            host[idx] = orig - eps
            fm = fn(*[array(host.astype(_np.float32)) if j == i else inputs[j]
                      for j in range(len(inputs))]).sum().asnumpy()
            host[idx] = orig
            num[idx] = (fp - fm) / (2 * eps)
            it.iternext()
        _np.testing.assert_allclose(analytic[i], num, rtol=rtol, atol=atol,
                                    err_msg=f'gradient mismatch for input {i}')


def check_consistency(fn, inputs, ctx_list=None, rtol=None, atol=None):
    """Same computation across contexts/dtypes (reference
    test_utils.py:check_consistency)."""
    ctx_list = ctx_list or [cpu(), default_context()]
    outs = []
    for ctx in ctx_list:
        xs = [x.as_in_context(ctx) for x in inputs]
        outs.append(_as_np(fn(*xs)))
    for o in outs[1:]:
        assert_almost_equal(outs[0], o, rtol=rtol, atol=atol)


def simple_forward(fn, *inputs):
    out = fn(*[array(x) if not isinstance(x, NDArray) else x
               for x in inputs])
    return out.asnumpy() if isinstance(out, NDArray) else \
        tuple(o.asnumpy() for o in out)


def discard_stderr(*a, **kw):
    import contextlib
    import io
    return contextlib.redirect_stderr(io.StringIO())


class DummyIter:
    pass


def list_gpus():
    from .context import num_gpus
    return list(range(num_gpus()))


def environment(*args):
    """with_environment ctx manager (reference tests common.py:313)."""
    import contextlib
    import os as _os

    @contextlib.contextmanager
    def ctx():
        key, value = args
        old = _os.environ.get(key)
        if value is None:
            _os.environ.pop(key, None)
        else:
            _os.environ[key] = str(value)
        try:
            yield
        finally:
            if old is None:
                _os.environ.pop(key, None)
            else:
                _os.environ[key] = old
    return ctx()


def check_symbolic_forward(sym, inputs, expected, rtol=1e-4, atol=1e-5,
                           ctx=None):
    """Reference test_utils.py:1190 — bind inputs, compare outputs."""
    args = sym.list_arguments()
    if isinstance(inputs, (list, tuple)):
        bindings = dict(zip(args, inputs))
    else:
        bindings = dict(inputs)
    bindings = {k: v if isinstance(v, NDArray) else array(v, ctx=ctx)
                for k, v in bindings.items()}
    outs = sym.eval(**bindings)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    assert len(outs) == len(expected), \
        f'{len(outs)} outputs vs {len(expected)} expected'
    for got, want in zip(outs, expected):
        assert_almost_equal(got, want, rtol=rtol, atol=atol)
    return outs


def check_symbolic_backward(sym, inputs, out_grads, expected_grads,
                            rtol=1e-2, atol=1e-4, ctx=None):
    """Reference test_utils.py check_symbolic_backward — grads of a bound
    symbol w.r.t. its arguments against expected values."""
    from . import autograd

    args = sym.list_arguments()
    if isinstance(inputs, (list, tuple)):
        inputs = dict(zip(args, inputs))
    nd_in = {k: v if isinstance(v, NDArray) else array(v, ctx=ctx)
             for k, v in inputs.items()}
    for v in nd_in.values():
        v.attach_grad()
    with autograd.record():
        outs = sym.eval(**nd_in)
        if not isinstance(out_grads, (list, tuple)):
            out_grads = [out_grads]
    heads = list(outs)
    grads = [g if isinstance(g, NDArray) else array(g, ctx=ctx)
             for g in out_grads]
    from . import _tape
    _tape.backward(heads, grads)
    if isinstance(expected_grads, (list, tuple)):
        expected_grads = dict(zip(args, expected_grads))
    result = {}
    for name, want in expected_grads.items():
        got = nd_in[name].grad
        if want is not None:
            assert_almost_equal(got, want, rtol=rtol, atol=atol)
        result[name] = got
    return result
