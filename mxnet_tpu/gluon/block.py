"""Gluon Block / HybridBlock.

Reference: ``python/mxnet/gluon/block.py`` (Block:201, __call__:705,
HybridBlock:859, hybridize:1217, graph capture _get_graph_v2:959 via
deferred-compute tracing, _build_cache:993 → CachedOp, export:1299,
SymbolBlock:1485).

TPU re-design of the capture pipeline (SURVEY §3.2): ``hybridize()`` makes
the next call trace ``forward`` with jax tracers flowing through the same
NDArray ops (the role of deferred compute, imperative.h:244-250) and
compiles an XLA executable with ``jax.jit`` (the role of CachedOp,
cached_op.cc:776). The compiled step:

* is cached per (input shapes/dtypes, train-mode) — ≙ CachedOpState keyed
  by shape/type inference results (cached_op.cc:168 SetForwardGraph);
* records as ONE node on the autograd tape (≙ RecordOp("_CachedOp"),
  cached_op.cc:836-844) whose VJP is the XLA-differentiated executable —
  so ``loss.backward()`` runs a compiled backward the way
  CachedOp::Backward (:1016) does;
* returns auxiliary-state updates (BN running stats) as extra outputs that
  are written back after the call — the functional analog of the
  reference's mutable aux states;
* static_alloc maps to XLA buffer donation; bulking/fusion are XLA's job.
"""

import os
import re
import threading
import warnings

import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray, array
from .parameter import Constant, DeferredInitializationError, Parameter
from .. import _rng, _tape

_BLOCK_TRACE = threading.local()


def _trace_state():
    if not hasattr(_BLOCK_TRACE, 'aux_writes'):
        _BLOCK_TRACE.aux_writes = None
    return _BLOCK_TRACE


def is_tracing():
    """True while a HybridBlock forward is being traced for compilation."""
    return _trace_state().aux_writes is not None


def record_aux_update(param, value):
    """Layers call this to update an auxiliary state (e.g. BN running
    mean). Eagerly: rebind now (keeping a pending bulked value lazy).
    Tracing: collected as an extra output of the compiled graph. Accepts
    an NDArray or a raw array."""
    from ..ndarray.ndarray import NDArray as _ND
    st = _trace_state()
    if st.aux_writes is not None:
        raw = value._data if isinstance(value, _ND) else value
        st.aux_writes[id(param)] = (param, raw)
    elif isinstance(value, _ND):
        for c in list(param._data):
            param._data[c]._adopt_lazy(value)
    else:
        for c in list(param._data):
            param._data[c]._rebind(value)


class ParameterDict(dict):
    """Ordered name->Parameter mapping with batch helpers (the surviving
    surface of the reference's ParameterDict after the 2.0 API cleanup)."""

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for param in self.values():
            param.initialize(init=init, ctx=ctx, force_reinit=force_reinit)

    def zero_grad(self):
        for param in self.values():
            param.zero_grad()

    def setattr(self, name, value):
        for param in self.values():
            setattr(param, name, value)

    def reset_ctx(self, ctx):
        for param in self.values():
            param.reset_ctx(ctx)

    def save(self, filename, strip_prefix=''):
        from ..model import save_ndarray_map
        data = {}
        for name, param in self.items():
            if name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            data[name] = param.data()
        save_ndarray_map(filename, data)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, cast_dtype=False, dtype_source='current'):
        from ..model import load_ndarray_map
        loaded = load_ndarray_map(filename)
        for name, param in self.items():
            if name in loaded:
                param.set_data(loaded[name])
            elif not allow_missing:
                raise KeyError(f'Parameter {name} missing in {filename}')


class _BlockScope:
    pass


class Block:
    """Base building block (reference gluon/block.py:201)."""

    def __init__(self, prefix=None, params=None):
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []
        self._shared = params
        self._ctx = None

    # ----------------------------------------------------------- registration
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get('_children')
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            existing = self.__dict__.get('_reg_params')
            if existing is not None:
                existing[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block
        return block

    @property
    def params(self):
        """Direct parameters of this block (no descendants)."""
        return ParameterDict(self._reg_params)

    def collect_params(self, select=None):
        """All parameters in this block's subtree, structurally named
        (reference block.py collect_params)."""
        out = ParameterDict()
        self._collect_params_with_prefix(out, '')
        if select is not None:
            pattern = re.compile(select)
            out = ParameterDict({k: v for k, v in out.items()
                                 if pattern.match(k)})
        return out

    def _collect_params_with_prefix(self, out, prefix):
        for name, param in self._reg_params.items():
            full = f'{prefix}{name}'
            param._structure_name = full
            out[full] = param
        for name, child in self._children.items():
            child._collect_params_with_prefix(out, f'{prefix}{name}.')

    # ------------------------------------------------------------------ hooks
    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------------ state
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        """Reference block.py initialize — collects + initializes."""
        self._ctx = ctx
        self.collect_params().initialize(init=init, ctx=ctx,
                                         force_reinit=force_reinit)

    def _initialized_once(self):
        params = self.collect_params()
        return all(p._data is not None or p._deferred_init is not None
                   for p in params.values()) and bool(params)

    def cast(self, dtype):
        for param in self.collect_params().values():
            param.cast(dtype)
        return self

    def reset_ctx(self, ctx):
        self.collect_params().reset_ctx(ctx)

    def zero_grad(self):
        self.collect_params().zero_grad()

    def share_parameters(self, shared):
        """Reference block.py share_parameters (gluon 2.0 weight sharing)."""
        own = self.collect_params()
        for name, param in shared.items():
            if name in own:
                self._set_param_by_path(name, param)
        return self

    def _set_param_by_path(self, path, param):
        parts = path.split('.')
        block = self
        for p in parts[:-1]:
            block = block._children[p]
        block._reg_params[parts[-1]] = param
        object.__setattr__(block, parts[-1], param)

    # ----------------------------------------------------------- save / load
    def save_parameters(self, filename, deduplicate=False):
        """Reference block.py:339 (NDArray-map format)."""
        self.collect_params().save(filename)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source='current'):
        """Reference block.py:375."""
        params = self.collect_params()
        if not self._initialized_once():
            self.initialize(ctx=ctx)
        params.load(filename, ctx=ctx, allow_missing=allow_missing,
                    ignore_extra=ignore_extra)

    def save(self, prefix):
        self.save_parameters(f'{prefix}-model.params.npz')

    def load(self, prefix):
        self.load_parameters(f'{prefix}-model.params.npz')

    # ------------------------------------------------------------------- call
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        from ..visualization import print_summary
        return print_summary(self, inputs[0].shape if inputs else
                             (1, 3, 224, 224))

    def __repr__(self):
        s = f'{type(self).__name__}('
        for name, child in self._children.items():
            s += f'\n  ({name}): {child!r}'.replace('\n', '\n  ')
        return s + ('\n)' if self._children else ')')

    def hybridize(self, active=True, **kwargs):
        """Plain Blocks recurse into children (reference block.py:693)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    @property
    def compile_count(self):
        """Total XLA executables built for this block's subtree since
        construction (monotonic; survives re-hybridize/clear). The
        serving layer (``mx.serve``) asserts this stays flat after
        bucket prewarm — the zero-recompiles-under-traffic guarantee."""
        return sum(child.compile_count for child in self._children.values())


class _CachedGraph:
    """Compiled-executable cache for one HybridBlock (≙ CachedOp,
    src/imperative/cached_op.h:463)."""

    def __init__(self, block, static_alloc=False, static_shape=False,
                 backend=None, flags=None, remat=False, check=False,
                 donate_inputs=False):
        self.block = block
        self.static_alloc = static_alloc
        self.static_shape = static_shape
        self.backend = backend
        self.remat = remat or os.environ.get(
            'MXNET_BACKWARD_DO_MIRROR', '') == '1'
        # lint the traced graph after the first compile (mx.analysis)
        self.check = check
        self._checked = False
        # opt-in: donate input activations to XLA (caller promises not
        # to reuse the passed buffers); never the default — gluon
        # callers keep live NDArray handles to their inputs
        self.donate_inputs = donate_inputs
        # monotonic count of executables built (never reset by clear():
        # the serving layer's zero-recompiles-after-warmup guarantee is
        # checked against this, so re-hybridize churn must show up too)
        self.compiles = 0
        self._compiled = {}
        self._out_trees = {}       # per cache entry: output pytree structure
        self._param_order = None
        self._monitor_callbacks = []
        # serializes tracing + recorded calls; see __call__ (reference:
        # src/imperative/cached_op_threadsafe.cc thread-safe CachedOp)
        self._lock = threading.RLock()
        self._race = None
        from ..analysis import race as _race
        if _race.enabled():
            # declared level 'block.graph' (analysis/locks.py). Only
            # cache WRITES are annotated: the lock-free _ready probe on
            # the steady-state inference path is by design (re-checked
            # under the lock) and must not be reported.
            self._lock = _race.tracked(self._lock, 'block.graph')
            self._race = _race.shared_state('block._CachedGraph.cache',
                                            guard=self._lock)
        self._ready = set()        # keys whose first call fully completed
        # set when the graph has data-dependent shapes (boolean_mask,
        # np.unique, ...) that abstract jit tracing cannot express —
        # the block then runs eagerly, like the reference CachedOp with
        # config.is_dynamic (cached_op.h:455: "uses dynamic shape" →
        # op-by-op execution)
        self._dynamic = False

    def clear(self):
        with self._lock:
            if self._race is not None:
                self._race.write()
            self._compiled.clear()
            self._out_trees.clear()
            self._ready.clear()
            self._param_order = None

    def _params(self):
        if self._param_order is None:
            params = self.block.collect_params()
            main, aux = [], []
            for p in params.values():
                (aux if p.grad_req == 'null' else main).append(p)
            self._param_order = (main, aux)
        return self._param_order

    def _sharding_plan(self, ctx, in_nds):
        """Resolved shardings for one compile under an active
        ``mx.sharding`` context: ``(in_shardings kwarg, param specs,
        input specs)``. Params match the rule registry by structural
        name; inputs take the batch spec (leading dim on the data
        axis). Parameter buffers are placed on the mesh here, once —
        later calls dispatch on already-sharded arrays."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as _P

        main, aux = self._params()
        rules = ctx.rules_for_block(self.block)
        # names relative to THIS block, resolved fresh: a child-level
        # collect_params() call (infer_shape tracing a child's cached
        # graph, a user poking net.output) re-stamps _structure_name
        # with child-relative names, so the cached stamp cannot be
        # trusted for rule matching
        fresh = {id(p): k for k, p in self.block.collect_params().items()}
        specs = {}
        for p in list(main) + list(aux):
            name = fresh.get(id(p)) or p.name
            spec = ctx.spec_for(name, p.shape, rules)
            specs[id(p)] = spec
            sh = NamedSharding(ctx.mesh, spec)
            for c, nd in list(p._data.items()):
                if getattr(nd._data, 'sharding', None) != sh:
                    nd._rebind(jax.device_put(nd._data, sh))
            p._sharding_spec = spec
            p._sharding_mesh = ctx.mesh
        in_specs = tuple(ctx.batch_spec(x.shape) for x in in_nds)
        # rng key and graph inputs arrive as fresh single-device arrays
        # each call: leave their entry None (jax.jit: inherit from the
        # argument) and let the with_sharding_constraint injected in
        # pure_fn distribute them; a committed explicit sharding here
        # would make pjit reject the host-resident batch outright.
        in_shardings = (
            None,
            tuple(None for _ in in_specs),
            tuple(NamedSharding(ctx.mesh, specs[id(p)]) for p in main),
            tuple(NamedSharding(ctx.mesh, specs[id(p)]) for p in aux),
        )
        return in_shardings, specs, in_specs

    def _build(self, shapes_key, train_mode, n_in, treedef, donate=(),
               ctx=None, in_nds=()):
        import jax

        jit_kwargs = {}
        aux_specs = None
        in_specs = None
        if ctx is not None:
            in_shardings, specs, in_specs = self._sharding_plan(ctx,
                                                                in_nds)
            jit_kwargs['in_shardings'] = in_shardings
            _, aux = self._params()
            aux_specs = tuple(specs[id(p)] for p in aux)
        pure_fn = self._make_pure(shapes_key, train_mode, treedef,
                                  ctx=ctx, aux_specs=aux_specs)
        if donate:
            # static_alloc buffer reuse (≙ the reference's persistent
            # workspace): donate the mutable aux state (argnum 3, BN
            # running stats) on recorded-train entries so XLA updates
            # it in place (input_output_alias), and the inputs (argnum
            # 1) when the caller opted in via donate_inputs. __call__
            # computes the tuple; inference entries never donate aux —
            # lock-free threads share those buffers. The donation-audit
            # rule (mx.analysis) machine-checks the aliasing actually
            # happens.
            jit_kwargs['donate_argnums'] = tuple(donate)
        if self.remat:
            # recompute activations in backward instead of storing them
            # (reference backward mirroring, MXNET_BACKWARD_DO_MIRROR)
            pure_fn = jax.checkpoint(pure_fn)
        jitted = jax.jit(pure_fn, **jit_kwargs)
        if ctx is None:
            return jitted
        # rng key / inputs arrive as committed single-device arrays each
        # call while the params are committed to the mesh — jax rejects
        # mixed device sets, so place them on the mesh at dispatch.
        # device_put is a traceable primitive, so the autograd vjp
        # re-trace of this wrapper stays valid.
        from jax.sharding import NamedSharding, PartitionSpec as _P
        key_sh = NamedSharding(ctx.mesh, _P())
        in_shs = tuple(NamedSharding(ctx.mesh, s) for s in in_specs)

        def sharded_fn(rng_key, in_raws, main_raws, aux_raws):
            rng_key = jax.device_put(rng_key, key_sh)
            in_raws = tuple(
                jax.device_put(r, sh)
                if getattr(r, 'ndim', None) is not None else r
                for r, sh in zip(in_raws, in_shs))
            return jitted(rng_key, in_raws, main_raws, aux_raws)

        return sharded_fn

    def _make_pure(self, shapes_key, train_mode, treedef, ctx=None,
                   aux_specs=None):
        import jax

        main, aux = self._params()

        if ctx is not None:
            # rule-tagged activation boundaries: constrain graph inputs
            # and outputs to the batch spec (leading dim on the data
            # axis) and aux write-backs to their param spec, so GSPMD
            # propagation anchors at the graph edge and the donated aux
            # output provably aliases its (identically sharded) input.
            # Interior boundaries: mx.sharding.constrain() — a no-op
            # outside the context, so models stay mesh-agnostic.
            from jax.sharding import NamedSharding

            def _bound(raw, spec=None):
                if getattr(raw, 'ndim', None) is None:
                    return raw
                spec = spec if spec is not None else ctx.batch_spec(
                    raw.shape)
                return jax.lax.with_sharding_constraint(
                    raw, NamedSharding(ctx.mesh, spec))
        else:
            def _bound(raw, spec=None):
                return raw

        def pure_fn(rng_key, in_raws, main_raws, aux_raws):
            # swap traced values into the parameters
            saved = []
            st = _trace_state()
            prev_aux = st.aux_writes
            st.aux_writes = {}
            prov = _rng.push_trace_provider(rng_key)
            prev_rec = _tape.set_recording(False)
            prev_train = _tape.set_training(train_mode)
            try:
                for p, raw in list(zip(main, main_raws)) + \
                        list(zip(aux, aux_raws)):
                    saved.append((p, p._data))
                    p._data = {c: NDArray(raw, ctx=c) for c in p._data}
                args = jax.tree.unflatten(
                    treedef, [NDArray(_bound(r)) for r in in_raws])
                out = self.block.forward(*args)
                out_leaves, out_tree = jax.tree.flatten(
                    out, is_leaf=lambda x: isinstance(x, NDArray))
                out_raws = [_bound(o._data) if isinstance(o, NDArray)
                            else o for o in out_leaves]
                if aux_specs is not None:
                    aux_out = [_bound(st.aux_writes[id(p)][1], spec)
                               if id(p) in st.aux_writes else ar
                               for p, ar, spec in zip(aux, aux_raws,
                                                      aux_specs)]
                else:
                    aux_out = [st.aux_writes[id(p)][1]
                               if id(p) in st.aux_writes else ar
                               for p, ar in zip(aux, aux_raws)]
                self._out_trees[shapes_key] = out_tree
                return tuple(out_raws), tuple(aux_out)
            finally:
                for p, data in saved:
                    p._data = data
                _tape.set_recording(prev_rec)
                _tape.set_training(prev_train)
                _rng.pop_trace_provider()
                st.aux_writes = prev_aux

        return pure_fn

    def __call__(self, args):
        import jax

        if self._dynamic:
            out = self.block.forward(*args)
            for cb in self._monitor_callbacks:
                cb(self.block, out)
            return out

        leaves, treedef = jax.tree.flatten(
            args, is_leaf=lambda x: isinstance(x, NDArray))
        in_nds = [x if isinstance(x, NDArray) else array(x) for x in leaves]
        main, aux = self._params()
        # the train flag alone decides the traced branch/behavior
        # (dropout, BN stats, detector training heads): record() turns
        # it on by default, autograd.train_mode() turns it on without
        # recording — eager and hybridized must agree in every scope
        train_mode = _tape.is_training()
        recording = _tape.is_recording()
        # Donation decision, per entry (and therefore part of the key):
        # aux state is donated only on recorded-train executables — those
        # run under the graph lock and immediately rebind the params to
        # the aliased outputs, so no other thread can keep a handle to
        # the donated buffer. donate_inputs is the caller's opt-in and
        # excluded while recording (input activations are backward
        # residuals).
        donate = ()
        if self.static_alloc and train_mode and recording and aux:
            donate += (3,)
        if self.donate_inputs and not recording:
            donate += (1,)
        donate = tuple(sorted(donate))
        # ambient mx.sharding context: its fingerprint joins the cache
        # key (a different mesh is a different XLA program — retracing
        # on mesh change is by design, the recompile-hazard rule
        # documents it as a non-hazard), and the entry compiles with
        # in_shardings derived from the partition-rule registry.
        from .. import sharding as _sharding
        ctx = _sharding.current()
        mesh_key = ctx.fingerprint() if ctx is not None else None
        # treedef is part of the key: same leaf shapes under different arg
        # nesting (or train/eval forwards with different output structures)
        # must not share a compiled entry or its output pytree
        key = (tuple((x.shape, str(x.dtype)) for x in in_nds), train_mode,
               donate, treedef, mesh_key)
        # Thread-safety contract (reference thread-safe CachedOp,
        # src/imperative/cached_op_threadsafe.cc:1-316; docs/threading.md):
        # compiled steady-state INFERENCE runs lock-free from N threads —
        # the executable is pure over its fetched inputs and jax dispatch
        # is thread-safe. The lock serializes (a) tracing, because
        # jax.jit traces lazily on first execution and pure_fn swaps
        # traced values into the SHARED Parameter payloads, and (b) any
        # autograd-recorded call, whose jax.vjp re-traces the jitted
        # function and re-enters that swap. Parameter snapshots on the
        # lock-free path still acquire the lock briefly so they can
        # never observe a mid-trace swap.
        if key in self._ready and not recording:
            with self._lock:
                # re-check under the lock: a concurrent clear()
                # (re-hybridize/cast while serving) may have emptied the
                # cache since the unlocked _ready probe. out_tree is
                # snapshotted here too — _execute must not re-read the
                # dict after the lock drops.
                jfn = self._compiled.get(key)
                out_tree = self._out_trees.get(key)
                main_nds = [p.data() for p in main]
                aux_raws = tuple(p.data()._data for p in aux)
            if jfn is not None and out_tree is not None:
                try:
                    return self._execute(args, key, jfn, in_nds, main_nds,
                                         aux_raws, out_tree)
                except RuntimeError as e:
                    if 'deleted' not in str(e).lower():
                        raise
                    # a recorded-train step donated the aux buffers this
                    # thread snapshotted between the lock release and
                    # dispatch; fall through to the serialized path,
                    # which re-snapshots the rebound (post-donation)
                    # state under the lock and executes while holding it
        with self._lock:
            if self._race is not None:
                self._race.write()
            if key not in self._compiled:
                self._compiled[key] = self._build(key, train_mode,
                                                  len(in_nds), treedef,
                                                  donate=donate, ctx=ctx,
                                                  in_nds=in_nds)
                self.compiles += 1
            jfn = self._compiled[key]
            main_nds = [p.data() for p in main]
            aux_raws = tuple(p.data()._data for p in aux)
            out = self._execute(args, key, jfn, in_nds, main_nds,
                                aux_raws, None)
            self._ready.add(key)
            if self.check and not self._checked:
                self._checked = True
                self._run_check(args, train_mode)
            return out

    def _run_check(self, args, train_mode):
        """hybridize(check=True): lint the just-compiled graph once and
        route findings through ``warnings`` (mx.analysis). Errors —
        including strict-promoted warnings under MXNET_ANALYSIS_STRICT=1
        — raise MXNetError."""
        from .. import analysis, profiler

        name = type(self.block).__name__
        try:
            graph = analysis.trace_block(self.block, *args,
                                         train=train_mode, name=name)
            report = analysis.lint_graph(graph)
        except Exception as e:   # noqa: BLE001 - lint must never kill a step
            warnings.warn(f'{name}: hybridize(check=True) could not lint '
                          f'the graph: {type(e).__name__}: {e}',
                          stacklevel=4)
            return
        self.block._analysis_report = report
        profiler.attach_analysis(name, report)
        if os.environ.get('MXNET_ANALYSIS_COSTS', '1') != '0':
            try:
                cost = analysis.cost_of_graph(graph)
                self.block._cost_report = cost
                profiler.attach_cost(name, cost)
            except Exception as e:   # noqa: BLE001 - advisory only
                warnings.warn(f'{name}: cost model failed: '
                              f'{type(e).__name__}: {e}', stacklevel=4)
        if report.findings:
            warnings.warn(str(report), stacklevel=4)
        report.raise_if_errors()

    def _execute(self, args, key, jfn, in_nds, main_nds, aux_raws,
                 out_tree):
        import jax
        from ..ops.registry import Op, apply_op, DynamicShapeError

        main, aux = self._params()
        rng_key = _rng.next_key()
        n_in = len(in_nds)
        n_aux = len(aux)

        def fn(*raws):
            ins = raws[:n_in]
            ps = raws[n_in:]
            outs, aux_out = jfn(rng_key, tuple(ins), tuple(ps), aux_raws)
            return tuple(outs) + tuple(aux_out)

        op = Op('_CachedOp', fn, differentiable=True)
        # predict-record mode defers jax.vjp to backward() time
        # (_tape.py); that re-trace re-enters pure_fn's shared-Parameter
        # payload swap and must hold this graph's lock (ADVICE r4)
        op.vjp_lock = self._lock
        try:
            res = apply_op(op, in_nds + main_nds, fn, name='_CachedOp',
                           lift=False)
        except DynamicShapeError:
            # a dynamic-output-shape op inside the graph (boolean_mask,
            # unique, ...): permanently switch this block to eager
            # op-by-op execution (reference dynamic-shape CachedOp).
            # Other tracing errors — e.g. Python control flow on traced
            # values — propagate unchanged so user bugs stay visible.
            # The failed entry is dropped so a later clear()+
            # re-hybridize can retry compilation.
            self._dynamic = True
            with self._lock:
                if self._race is not None:
                    self._race.write()
                self._compiled.pop(key, None)
                self._out_trees.pop(key, None)
                self._ready.discard(key)
            warnings.warn(
                f'{type(self.block).__name__}: graph has data-dependent '
                'shapes; hybridize falls back to eager execution '
                '(reference CachedOp is_dynamic)', stacklevel=2)
            return self(args)
        if not isinstance(res, tuple):
            res = (res,)
        out_vals = res[:len(res) - n_aux] if n_aux else res
        aux_vals = res[len(res) - n_aux:] if n_aux else ()
        if aux:
            # BN-stat style rebinding mutates shared Parameters: keep it
            # under the lock so a concurrent snapshot reads a coherent set
            with self._lock:
                for p, v in zip(aux, aux_vals):
                    for c in list(p._data):
                        p._data[c]._rebind(v._data)
                    # aux outputs never need grad linkage
                    v._ag = None
        if out_tree is None:
            # locked path: the tree was written during this call's trace
            # and the caller still holds the graph lock
            out_tree = self._out_trees[key]
        out = jax.tree.unflatten(out_tree, list(out_vals))
        for cb in self._monitor_callbacks:
            cb(self.block, out)
        return out


class HybridBlock(Block):
    """Reference gluon/block.py:859 — traceable/compilable Block."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_graph = None
        self._first_forward_done = False

    def hybridize(self, active=True, backend=None, backend_opts=None,
                  static_alloc=True, static_shape=False, inline_limit=2,
                  forward_bulk_size=None, backward_bulk_size=None,
                  remat=False, check=False, donate_inputs=False, **kwargs):
        """Reference block.py:1217. backend= selected subgraph backends in
        the reference (optimize_for); the whole graph goes to XLA here.

        ``remat=True`` wraps the compiled forward in ``jax.checkpoint``:
        backward recomputes activations instead of keeping them — the
        reference's backward-mirroring memory trade
        (MXNET_BACKWARD_DO_MIRROR, src/nnvm/gradient.cc:58-77), but as a
        per-block switch.

        ``check=True`` lints the traced graph right after the first
        compile (``mx.analysis``: dtype promotion, captured constants,
        recompile hazards, host transfers, dead code) and reports
        findings through ``warnings``; error findings — or any finding
        under ``MXNET_ANALYSIS_STRICT=1`` — raise :class:`MXNetError`.

        ``donate_inputs=True`` donates input activation buffers to XLA
        on non-recorded entries (buffer reuse — the caller must not
        touch the passed arrays after the call). Mutable aux state (BN
        running stats) is donated automatically on recorded-train
        entries under ``static_alloc``; the ``donation-audit`` analysis
        rule verifies the aliasing actually happens."""
        self._active = active
        self._cached_graph = _CachedGraph(
            self, static_alloc=static_alloc, static_shape=static_shape,
            backend=backend, remat=remat, check=check,
            donate_inputs=donate_inputs) if active else None
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def optimize_for(self, x, *args, backend=None, clear=True, **kwargs):
        """Reference block.py:1038 — partition for a backend. XLA compiles
        the whole graph; this hybridizes + warms the cache."""
        self.hybridize(True)
        return self(x, *args)

    def pure_function(self, *args, train=False):
        """Export this block's forward as a pure jax function — the
        TPU-idiomatic escape hatch for building fully-fused training
        programs (lax.scan over steps, pjit over meshes) where the
        per-step Python dispatch of the imperative path would dominate.

        Returns ``(fn, in_raws, main_raws, aux_raws)`` with
        ``fn(rng_key, in_raws, main_raws, aux_raws) ->
        (out_raws_tuple, new_aux_raws_tuple)`` pure and traceable.
        ``main_raws`` are the trainable parameters (grad_req != 'null'),
        ``aux_raws`` the rest (e.g. BatchNorm running stats — returned
        updated when ``train=True``). No reference analog: CachedOp has
        no user-facing pure form; this is new TPU-first surface."""
        import jax
        if not isinstance(self._cached_graph, _CachedGraph):
            self.hybridize(True)
        graph = self._cached_graph
        if not self._first_forward_done:
            self(*args)  # materialize deferred params
        leaves, treedef = jax.tree.flatten(
            args, is_leaf=lambda x: isinstance(x, NDArray))
        in_raws = tuple(x._data if isinstance(x, NDArray)
                        else array(x)._data for x in leaves)
        main, aux = graph._params()
        fn = graph._make_pure(None, train, treedef)
        main_raws = tuple(p.data()._data for p in main)
        aux_raws = tuple(p.data()._data for p in aux)
        return fn, in_raws, main_raws, aux_raws

    @property
    def compile_count(self):
        """See :attr:`Block.compile_count`; adds this block's own cache."""
        own = self._cached_graph.compiles if isinstance(
            self._cached_graph, _CachedGraph) else 0
        return own + sum(c.compile_count for c in self._children.values())

    def prewarm(self, input_specs, dtype='float32'):
        """Compile executables for a declared set of input shapes before
        they ever see traffic (the serving layer's bucket warmup; no
        reference analog — CachedOp compiles lazily per shape).

        ``input_specs``: iterable of entries, each either a shape tuple
        for a single-input block, a ``(shape, dtype)`` pair, or a tuple
        of shape tuples for multi-input blocks. Runs one non-recorded
        forward per entry (discarding outputs) so the compile cache holds
        every declared bucket. Returns the number of new executables
        built (0 when everything was already warm)."""
        before = self.compile_count
        for spec in input_specs:
            d = dtype
            if (isinstance(spec, tuple) and len(spec) == 2
                    and isinstance(spec[0], tuple)
                    and isinstance(spec[1], str)):
                spec, d = spec
            if isinstance(spec, tuple) and spec \
                    and isinstance(spec[0], tuple):
                shapes = spec
            else:
                shapes = (tuple(spec),)
            args = [array(_np.zeros(s, dtype=_np.dtype(d))) for s in shapes]
            prev = _tape.set_recording(False)
            try:
                first = not self._first_forward_done
                self(*args)
                if first:
                    # the very first call runs the shape-inference
                    # forward without populating the compile cache —
                    # dispatch again so this bucket is genuinely warm
                    self(*args)
            finally:
                _tape.set_recording(prev)
        return self.compile_count - before

    def infer_shape(self, *args):
        """Reference block.py:1278 — resolve deferred parameter shapes from
        input shapes by abstract evaluation (no FLOPs)."""
        import jax
        leaves, treedef = jax.tree.flatten(
            args, is_leaf=lambda x: isinstance(x, NDArray))

        def run(*raw):
            nds = jax.tree.unflatten(treedef, [NDArray(r) for r in raw])
            prev = _tape.set_recording(False)
            try:
                self.forward(*nds)
            finally:
                _tape.set_recording(prev)
            return 0

        try:
            jax.eval_shape(run, *[x._data for x in leaves])
        except DeferredInitializationError:
            pass

    def register_op_hook(self, callback, monitor_all=False):
        """Reference cached_op.cc:1212 RegisterOpHook — here a whole-graph
        monitor (per-op hooks would defeat XLA fusion)."""
        if self._cached_graph is not None:
            self._cached_graph._monitor_callbacks.append(callback)

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        if all(isinstance(a, NDArray) for a in args) and args:
            self._last_in_specs = [(a.shape, a.dtype) for a in args]
        from .. import _deferred_compute as _dc
        if self._active and self._cached_graph is not None and \
                self._first_forward_done and not _dc.is_deferred_compute() \
                and not is_tracing():
            # is_tracing(): inside a parent's graph capture children inline
            # into the parent executable (reference: CachedOp inline_limit /
            # whole-graph capture) instead of nesting compiled calls
            if kwargs:
                raise ValueError(
                    'keyword arguments are not supported when a HybridBlock '
                    'is hybridized (reference block.py raises the same); '
                    'pass them positionally or call hybridize(False)')
            out = self._cached_graph(args)
        else:
            out = self.forward(*args, **kwargs)
            self._first_forward_done = True
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        if hasattr(self, 'hybrid_forward'):
            # legacy hybrid_forward(F, x, **params) protocol (v1 graph mode)
            from .. import ndarray as F
            pdata = {name: p.data() for name, p in self._reg_params.items()}
            return self.hybrid_forward(F, *args, **pdata)
        raise NotImplementedError(
            f'{type(self).__name__} must implement forward')

    def _trace_symbol(self, *args):
        """Capture the (inference-mode) forward graph as a Symbol via
        deferred compute (≙ _get_graph_v2, reference block.py:959).

        ``args``: example NDArrays (or shape tuples) for the data inputs.
        Parameters become symbol variables named by their structural names,
        so the params file keys match ``symbol.list_arguments()``.
        """
        import jax

        from .. import _deferred_compute as dc

        in_specs = []
        for a in args:
            if isinstance(a, NDArray):
                in_specs.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
            else:
                in_specs.append(jax.ShapeDtypeStruct(tuple(a), _np.float32))
        in_names = ['data'] if len(args) == 1 else \
            [f'data{i}' for i in range(len(args))]

        params = self.collect_params()
        p_items = list(params.items())
        p_specs = [jax.ShapeDtypeStruct(p.shape, _np.dtype(p.dtype))
                   for _, p in p_items]
        n_in = len(in_specs)
        captured = {}
        st = _trace_state()

        def run(*raws):
            saved = []
            prev_rec = _tape.set_recording(False)
            prev_train = _tape.set_training(False)
            prev_aux = st.aux_writes
            st.aux_writes = {}
            try:
                with dc.context():
                    nds = [NDArray(r) for r in raws[:n_in]]
                    dc.set_variable(nds, in_names)
                    for (name, p), r in zip(p_items, raws[n_in:]):
                        nd = NDArray(r)
                        saved.append((p, p._data))
                        p._data = {c: nd for c in p._data}
                        dc.set_variable(nd, name)
                    out = self.forward(*nds)
                    outs = out if isinstance(out, (list, tuple)) else [out]
                    captured['sym'] = dc.get_symbol(list(outs))
                return 0
            finally:
                for p, data in saved:
                    p._data = data
                _tape.set_recording(prev_rec)
                _tape.set_training(prev_train)
                st.aux_writes = prev_aux

        jax.eval_shape(run, *(in_specs + p_specs))
        return captured['sym']

    def export(self, path, epoch=0, remove_amp_cast=True, input_shapes=None):
        """Reference block.py:1299 — serialize graph + params for
        deployment.

        Emits ``{path}-symbol.json`` (the role of model-symbol.json; loads
        back via :meth:`SymbolBlock.imports`) and
        ``{path}-{epoch:04d}.params.npz``. Input shapes come from the first
        compiled-cache entry, or pass ``input_shapes=[(...), ...]``.
        """
        from ..model import save_ndarray_map
        params = self.collect_params()
        if input_shapes is None:
            specs = getattr(self, '_last_in_specs', None)
            if not specs:
                raise ValueError(
                    'export() needs input shapes: run a forward first, or '
                    'pass input_shapes=[...] (the reference has the same '
                    'run-before-export requirement, block.py:1299)')
            import jax
            args = [NDArray(jax.ShapeDtypeStruct(s, d)) for s, d in specs]
        else:
            args = list(input_shapes)
        param_path = f'{path}-{epoch:04d}.params.npz'
        sym = self._trace_symbol(*args)
        if not any(n.op == '_opaque' for n in sym._topo()):
            # hoisted constant buffers ride the params file beside weights
            data = dict({k: v.data() for k, v in params.items()},
                        **sym._aux)
            save_ndarray_map(param_path, data)
            sym.save(f'{path}-symbol.json')
            return f'{path}-symbol.json', param_path
        save_ndarray_map(param_path,
                         {k: v.data() for k, v in params.items()})
        # closure-dispatched layers (fused RNN etc.) can't serialize to
        # JSON — export the compiled graph as portable StableHLO instead
        return self._export_stablehlo(path, args), param_path

    def _export_stablehlo(self, path, args):
        """Portable serialized executable via jax.export (the deployment
        fallback for graphs containing closure-based ops)."""
        import jax
        from jax import export as jexport

        items = list(self.collect_params().items())
        st = _trace_state()

        def fn(in_raws, p_raws):
            saved = []
            prev_rec = _tape.set_recording(False)
            prev_train = _tape.set_training(False)
            prev_aux = st.aux_writes
            st.aux_writes = {}
            try:
                for (_, p), r in zip(items, p_raws):
                    saved.append((p, p._data))
                    p._data = {c: NDArray(r) for c in p._data}
                out = self.forward(*[NDArray(r) for r in in_raws])
                leaves, _ = jax.tree.flatten(
                    out, is_leaf=lambda x: isinstance(x, NDArray))
                return tuple(o._data if isinstance(o, NDArray) else o
                             for o in leaves)
            finally:
                for p, d in saved:
                    p._data = d
                _tape.set_recording(prev_rec)
                _tape.set_training(prev_train)
                st.aux_writes = prev_aux

        in_specs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                         for a in args)
        p_specs = tuple(jax.ShapeDtypeStruct(p.shape, _np.dtype(p.dtype))
                        for _, p in items)
        exp = jexport.export(jax.jit(fn))(in_specs, p_specs)
        out_path = f'{path}-symbol.stablehlo'
        with open(out_path, 'wb') as f:
            f.write(exp.serialize())
        return out_path


class SymbolBlock(HybridBlock):
    """Run a Symbol graph as a Block (reference block.py:1485).

    Every non-input variable of the symbol becomes a :class:`Parameter`
    (loaded from the params file or initialized), and ``forward`` replays
    the graph through the op registry — so autograd and re-hybridization
    both work on imported models.
    """

    def __init__(self, outputs, inputs, params=None):
        super().__init__()
        from ..symbol.symbol import Group, Symbol
        if not isinstance(outputs, Symbol):
            outputs = Group(list(outputs))
        self._sym = outputs
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self._input_names = [i if isinstance(i, str) else i.name
                             for i in inputs]
        shape_attrs = {n.name: (n.attrs.get('__shape__'),
                                n.attrs.get('__dtype__', 'float32'))
                       for n in self._sym._topo() if n.op == 'null'}
        self._sym_param_names = [n for n in self._sym.list_arguments()
                                 if n not in self._input_names]
        # hoisted constant buffers captured on an in-memory symbol load as
        # (non-trainable) parameters alongside any explicitly passed params
        params = dict(outputs._aux, **(params or {}))
        for name in self._sym_param_names:
            shape, dtype = shape_attrs.get(name, (None, 'float32'))
            p = Parameter(name, shape=shape, dtype=dtype,
                          allow_deferred_init=True)
            if name in params:
                v = params[name]
                if not isinstance(v, NDArray):
                    v = array(v)
                p.dtype = str(v.dtype)
                p.set_data(v)
            self._reg_params[name] = p

    @staticmethod
    def imports(symbol_file, input_names='data', param_file=None, ctx=None):
        """Load an exported model (reference block.py SymbolBlock.imports)."""
        from ..model import load_ndarray_map
        from ..symbol import load as sym_load
        sym = sym_load(symbol_file)
        params = load_ndarray_map(param_file) if param_file else {}
        if ctx is not None:
            params = {k: v.as_in_context(ctx) for k, v in params.items()}
        if isinstance(input_names, str):
            input_names = [input_names]
        return SymbolBlock(sym, list(input_names), params=params)

    def forward(self, *args):
        bindings = {}
        for name, a in zip(self._input_names, args):
            bindings[name] = a if isinstance(a, NDArray) else array(a)
        for name in self._sym_param_names:
            bindings[name] = self._reg_params[name].data()
        outs = self._sym._execute(bindings)
        return outs[0] if len(outs) == 1 else tuple(outs)
