"""``gluon.rnn`` (reference python/mxnet/gluon/rnn/)."""

from .rnn_cell import (RNNCell, LSTMCell, GRUCell, SequentialRNNCell,
                       DropoutCell, ModifierCell, ZoneoutCell, ResidualCell,
                       BidirectionalCell, HybridRecurrentCell, RecurrentCell)
from .rnn_layer import RNN, LSTM, GRU

# reference rnn_cell.py:755 — hybrid variant is the same class here (every
# cell is traceable)
HybridSequentialRNNCell = SequentialRNNCell
