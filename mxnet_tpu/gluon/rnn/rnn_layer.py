"""Fused multi-layer RNN/LSTM/GRU layers.

Reference: ``python/mxnet/gluon/rnn/rnn_layer.py`` backed by the fused
``_npx_rnn`` op with its cudnn path (src/operator/rnn.cc, rnn-inl.h). TPU
design: the time loop is a ``lax.scan`` — XLA compiles it into a single
fused while-loop with the gate matmuls batched on the MXU, which is the
role cuDNN's fused RNN kernels played. Bidirectional runs a reversed scan;
multi-layer stacks scans with optional inter-layer dropout.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..block import HybridBlock
from ..parameter import Parameter
from ...ndarray.ndarray import NDArray
from ...ops.registry import Op, apply_op
from ... import _rng, _tape


def _lstm_step(carry, x_t, wi, wh, bi, bh):
    h, c = carry
    gates = x_t @ wi.T + bi + h @ wh.T + bh
    hid = h.shape[-1]
    i, f, g, o = (gates[:, :hid], gates[:, hid:2 * hid],
                  gates[:, 2 * hid:3 * hid], gates[:, 3 * hid:])
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c = f * c + i * g
    h = o * jnp.tanh(c)
    return (h, c), h


def _gru_step(carry, x_t, wi, wh, bi, bh):
    (h,) = carry
    hid = h.shape[-1]
    gi = x_t @ wi.T + bi
    gh = h @ wh.T + bh
    r = jax.nn.sigmoid(gi[:, :hid] + gh[:, :hid])
    z = jax.nn.sigmoid(gi[:, hid:2 * hid] + gh[:, hid:2 * hid])
    n = jnp.tanh(gi[:, 2 * hid:] + r * gh[:, 2 * hid:])
    h = (1 - z) * n + z * h
    return (h,), h


def _rnn_step_tanh(carry, x_t, wi, wh, bi, bh):
    (h,) = carry
    h = jnp.tanh(x_t @ wi.T + bi + h @ wh.T + bh)
    return (h,), h


def _rnn_step_relu(carry, x_t, wi, wh, bi, bh):
    (h,) = carry
    h = jax.nn.relu(x_t @ wi.T + bi + h @ wh.T + bh)
    return (h,), h


_STEPS = {'lstm': (_lstm_step, 2, 4), 'gru': (_gru_step, 1, 3),
          'rnn_tanh': (_rnn_step_tanh, 1, 1),
          'rnn_relu': (_rnn_step_relu, 1, 1)}


class _RNNLayer(HybridBlock):
    """Base fused layer (reference rnn_layer.py:_RNNLayer)."""

    def __init__(self, mode, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer='zeros',
                 h2h_bias_initializer='zeros', **kwargs):
        super().__init__(**kwargs)
        assert layout in ('TNC', 'NTC')
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        _, self._num_states, ngates = _STEPS[mode]
        for layer in range(num_layers):
            for d in range(self._dir):
                suffix = '_l' if d == 0 else '_r'
                in_size = input_size if layer == 0 else \
                    hidden_size * self._dir
                setattr(self, f'{suffix[1]}{layer}_i2h_weight', Parameter(
                    f'{suffix[1]}{layer}_i2h_weight',
                    shape=(ngates * hidden_size, in_size),
                    init=i2h_weight_initializer, allow_deferred_init=True))
                setattr(self, f'{suffix[1]}{layer}_h2h_weight', Parameter(
                    f'{suffix[1]}{layer}_h2h_weight',
                    shape=(ngates * hidden_size, hidden_size),
                    init=h2h_weight_initializer, allow_deferred_init=True))
                setattr(self, f'{suffix[1]}{layer}_i2h_bias', Parameter(
                    f'{suffix[1]}{layer}_i2h_bias',
                    shape=(ngates * hidden_size,),
                    init=i2h_bias_initializer, allow_deferred_init=True))
                setattr(self, f'{suffix[1]}{layer}_h2h_bias', Parameter(
                    f'{suffix[1]}{layer}_h2h_bias',
                    shape=(ngates * hidden_size,),
                    init=h2h_bias_initializer, allow_deferred_init=True))

    def _params_of(self, layer, d):
        s = 'l' if d == 0 else 'r'
        return [getattr(self, f'{s}{layer}_{n}') for n in
                ('i2h_weight', 'h2h_weight', 'i2h_bias', 'h2h_bias')]

    def state_info(self, batch_size=0):
        return [{'shape': (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}] * self._num_states

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        return [F.zeros((self._num_layers * self._dir, batch_size,
                         self._hidden_size))
                for _ in range(self._num_states)]

    def _infer(self, x):
        in_size = x.shape[-1]
        for layer in range(self._num_layers):
            for d in range(self._dir):
                wi, wh, bi, bh = self._params_of(layer, d)
                if wi.shape[1] == 0:
                    wi.shape = (wi.shape[0],
                                in_size if layer == 0
                                else self._hidden_size * self._dir)
                for p in (wi, wh, bi, bh):
                    if p._data is None:
                        p._finish_deferred_init()

    def forward(self, inputs, states=None):
        self._infer(inputs)
        layout = self._layout
        batch_axis = layout.find('N')
        batch = inputs.shape[batch_axis]
        return_states = states is not None
        if states is None:
            states = self.begin_state(batch)
        if not isinstance(states, (list, tuple)):
            states = [states]

        step_fn, n_states, _ = _STEPS[self._mode]
        n_layers, n_dir, hid = self._num_layers, self._dir, self._hidden_size
        dropout = self._dropout if _tape.is_training() else 0.0

        params = []
        for layer in range(n_layers):
            for d in range(n_dir):
                params.extend(p.data() for p in self._params_of(layer, d))

        arrays = [inputs] + [s for s in states] + params
        n_in = 1 + len(states)

        def fn(*raws):
            x = raws[0]
            st = raws[1:n_in]
            ps = raws[n_in:]
            if layout == 'NTC':
                x = jnp.swapaxes(x, 0, 1)  # scan over time-major
            out = x
            final_states = [[] for _ in range(n_states)]
            pi = 0
            for layer in range(n_layers):
                outs_dir = []
                for d in range(n_dir):
                    wi, wh, bi, bh = ps[pi:pi + 4]
                    pi += 4
                    idx = layer * n_dir + d
                    init = tuple(st[k][idx] for k in range(n_states))
                    seq = out if d == 0 else jnp.flip(out, 0)
                    carry, ys = lax.scan(
                        lambda c, xt: step_fn(c, xt, wi, wh, bi, bh),
                        init, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    outs_dir.append(ys)
                    for k in range(n_states):
                        final_states[k].append(carry[k])
                out = outs_dir[0] if n_dir == 1 else \
                    jnp.concatenate(outs_dir, axis=-1)
                if dropout and layer < n_layers - 1:
                    # key drawn INSIDE the traced fn: under hybridize the
                    # trace provider supplies a per-call key input, so the
                    # dropout mask varies per step instead of baking one
                    # mask into the captured graph
                    key = _rng.next_key()
                    mask = jax.random.bernoulli(
                        jax.random.fold_in(key, layer), 1 - dropout,
                        out.shape)
                    out = jnp.where(mask, out / (1 - dropout), 0.0)
            if layout == 'NTC':
                out = jnp.swapaxes(out, 0, 1)
            finals = [jnp.stack(fs) for fs in final_states]
            return tuple([out] + finals)

        op = Op(f'_rnn_{self._mode}', fn, differentiable=True)
        res = apply_op(op, arrays, fn, name=f'rnn_{self._mode}')
        out, new_states = res[0], list(res[1:])
        if return_states:
            return out, new_states
        return out

    def __repr__(self):
        return (f'{type(self).__name__}({self._hidden_size}, '
                f'num_layers={self._num_layers})')


class RNN(_RNNLayer):
    """Reference rnn_layer.py:RNN."""

    def __init__(self, hidden_size, num_layers=1, activation='tanh',
                 layout='TNC', dropout=0, bidirectional=False,
                 input_size=0, **kwargs):
        super().__init__(f'rnn_{activation}', hidden_size, num_layers,
                         layout, dropout, bidirectional, input_size,
                         **kwargs)


class LSTM(_RNNLayer):
    """Reference rnn_layer.py:LSTM."""

    def __init__(self, hidden_size, num_layers=1, layout='TNC', dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__('lstm', hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)


class GRU(_RNNLayer):
    """Reference rnn_layer.py:GRU."""

    def __init__(self, hidden_size, num_layers=1, layout='TNC', dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__('gru', hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)
