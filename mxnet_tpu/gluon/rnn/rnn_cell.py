"""RNN cells (reference python/mxnet/gluon/rnn/rnn_cell.py).

Cell-level API + ``unroll``. On TPU, unrolling uses ``lax.scan`` through the
layer API (rnn_layer.py) for compiled loops; the Python unroll here matches
the reference's step-by-step semantics for cell composition.
"""

from ..block import HybridBlock
from ..parameter import Parameter
from ...ops.registry import get_op, invoke
from ... import _tape


def _op(name, *args, **kw):
    return invoke(get_op(name), args, kw)


class RecurrentCell(HybridBlock):
    """Reference rnn_cell.py:RecurrentCell."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = info['shape']
            states.append(F.zeros(shape))
        return states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        """Reference rnn_cell.py unroll."""
        axis = layout.find('T')
        batch_axis = layout.find('N')
        if isinstance(inputs, (list, tuple)):
            seq = list(inputs)
            batch = seq[0].shape[0]
        else:
            batch = inputs.shape[batch_axis]
            seq = [
                inputs[(slice(None),) * axis + (t,)]
                for t in range(length)]
        states = begin_state if begin_state is not None else \
            self.begin_state(batch)
        outputs = []
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
        if merge_outputs:
            outputs = _op('stack', *outputs, axis=axis)
        return outputs, states

    def forward(self, inputs, states):
        raise NotImplementedError


HybridRecurrentCell = RecurrentCell


class RNNCell(RecurrentCell):
    """Elman RNN cell (reference rnn_cell.py:RNNCell)."""

    def __init__(self, hidden_size, activation='tanh', i2h_weight_initializer
                 =None, h2h_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = Parameter('i2h_weight',
                                    shape=(hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter('h2h_weight',
                                    shape=(hidden_size, hidden_size),
                                    init=h2h_weight_initializer,
                                    allow_deferred_init=True)
        self.i2h_bias = Parameter('i2h_bias', shape=(hidden_size,),
                                  init=i2h_bias_initializer,
                                  allow_deferred_init=True)
        self.h2h_bias = Parameter('h2h_bias', shape=(hidden_size,),
                                  init=h2h_bias_initializer,
                                  allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size), '__layout__':
                 'NC'}]

    def _infer(self, x):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (self._hidden_size, x.shape[-1])
            for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias,
                      self.h2h_bias):
                p._finish_deferred_init()

    def forward(self, inputs, states):
        self._infer(inputs)
        i2h = _op('fully_connected', inputs, self.i2h_weight.data(),
                  self.i2h_bias.data(), num_hidden=self._hidden_size)
        h2h = _op('fully_connected', states[0], self.h2h_weight.data(),
                  self.h2h_bias.data(), num_hidden=self._hidden_size)
        out = _op('activation', i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(RecurrentCell):
    """Reference rnn_cell.py:LSTMCell (gate order i, f, c, o as in the
    fused kernel src/operator/rnn_impl.h)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer='zeros',
                 h2h_bias_initializer='zeros', input_size=0,
                 activation='tanh', recurrent_activation='sigmoid',
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self.i2h_weight = Parameter('i2h_weight',
                                    shape=(4 * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter('h2h_weight',
                                    shape=(4 * hidden_size, hidden_size),
                                    init=h2h_weight_initializer,
                                    allow_deferred_init=True)
        self.i2h_bias = Parameter('i2h_bias', shape=(4 * hidden_size,),
                                  init=i2h_bias_initializer,
                                  allow_deferred_init=True)
        self.h2h_bias = Parameter('h2h_bias', shape=(4 * hidden_size,),
                                  init=h2h_bias_initializer,
                                  allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size)},
                {'shape': (batch_size, self._hidden_size)}]

    def _infer(self, x):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])
            for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias,
                      self.h2h_bias):
                p._finish_deferred_init()

    def forward(self, inputs, states):
        self._infer(inputs)
        h = self._hidden_size
        gates = _op('fully_connected', inputs, self.i2h_weight.data(),
                    self.i2h_bias.data(), num_hidden=4 * h) + \
            _op('fully_connected', states[0], self.h2h_weight.data(),
                self.h2h_bias.data(), num_hidden=4 * h)
        i = _op('sigmoid', gates[:, :h])
        f = _op('sigmoid', gates[:, h:2 * h])
        g = _op('tanh', gates[:, 2 * h:3 * h])
        o = _op('sigmoid', gates[:, 3 * h:])
        c = f * states[1] + i * g
        out = o * _op('tanh', c)
        return out, [out, c]


class GRUCell(RecurrentCell):
    """Reference rnn_cell.py:GRUCell (gate order r, z, n)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer='zeros',
                 h2h_bias_initializer='zeros', input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self.i2h_weight = Parameter('i2h_weight',
                                    shape=(3 * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter('h2h_weight',
                                    shape=(3 * hidden_size, hidden_size),
                                    init=h2h_weight_initializer,
                                    allow_deferred_init=True)
        self.i2h_bias = Parameter('i2h_bias', shape=(3 * hidden_size,),
                                  init=i2h_bias_initializer,
                                  allow_deferred_init=True)
        self.h2h_bias = Parameter('h2h_bias', shape=(3 * hidden_size,),
                                  init=h2h_bias_initializer,
                                  allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._hidden_size)}]

    def _infer(self, x):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])
            for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias,
                      self.h2h_bias):
                p._finish_deferred_init()

    def forward(self, inputs, states):
        self._infer(inputs)
        h = self._hidden_size
        i2h = _op('fully_connected', inputs, self.i2h_weight.data(),
                  self.i2h_bias.data(), num_hidden=3 * h)
        h2h = _op('fully_connected', states[0], self.h2h_weight.data(),
                  self.h2h_bias.data(), num_hidden=3 * h)
        r = _op('sigmoid', i2h[:, :h] + h2h[:, :h])
        z = _op('sigmoid', i2h[:, h:2 * h] + h2h[:, h:2 * h])
        n = _op('tanh', i2h[:, 2 * h:] + r * h2h[:, 2 * h:])
        out = (1 - z) * n + z * states[0]
        return out, [out]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells (reference rnn_cell.py:SequentialRNNCell)."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        out = []
        for cell in self._children.values():
            out.extend(cell.state_info(batch_size))
        return out

    def begin_state(self, batch_size=0, **kwargs):
        out = []
        for cell in self._children.values():
            out.extend(cell.begin_state(batch_size, **kwargs))
        return out

    def forward(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, st = cell(inputs, states[p:p + n])
            p += n
            next_states.extend(st)
        return inputs, next_states

    def __len__(self):
        return len(self._children)


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def forward(self, inputs, states):
        if self._rate > 0:
            inputs = _op('dropout', inputs, p=self._rate, axes=self._axes,
                         training=_tape.is_training())
        return inputs, states


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)


class ZoneoutCell(ModifierCell):
    """Reference rnn_cell.py:ZoneoutCell."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kwargs):
        super().__init__(base_cell, **kwargs)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def forward(self, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)
        if _tape.is_training():
            def mix(p, new, old):
                if p == 0.0 or old is None:
                    return new
                mask = _op('random_bernoulli', prob=1 - p, size=new.shape)
                return mask * new + (1 - mask) * old
            prev = self._prev_output
            out = mix(self.zoneout_outputs, next_output, prev)
            next_states = [mix(self.zoneout_states, ns, s)
                           for ns, s in zip(next_states, states)]
            self._prev_output = out
            return out, next_states
        return next_output, next_states


class ResidualCell(ModifierCell):
    def forward(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(RecurrentCell):
    """Reference rnn_cell.py:BidirectionalCell."""

    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return self.l_cell.state_info(batch_size) + \
            self.r_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.l_cell.begin_state(batch_size, **kwargs) + \
            self.r_cell.begin_state(batch_size, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        axis = layout.find('T')
        nl = len(self.l_cell.state_info())
        states = begin_state if begin_state is not None else \
            self.begin_state(inputs.shape[layout.find('N')])
        l_out, l_states = self.l_cell.unroll(
            length, inputs, states[:nl], layout, merge_outputs=False)
        rev = _op('flip', inputs, axis=axis)
        r_out, r_states = self.r_cell.unroll(
            length, rev, states[nl:], layout, merge_outputs=False)
        r_out = r_out[::-1]
        outs = [_op('concatenate', l, r, axis=-1)
                for l, r in zip(l_out, r_out)]
        if merge_outputs:
            outs = _op('stack', *outs, axis=axis)
        return outs, l_states + r_states

    def forward(self, inputs, states):
        raise NotImplementedError('use unroll for BidirectionalCell')
