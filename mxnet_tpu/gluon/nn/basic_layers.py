"""Core layers (reference python/mxnet/gluon/nn/basic_layers.py):
Sequential, Dense, Dropout, BatchNorm, LayerNorm, GroupNorm, InstanceNorm,
Embedding, Flatten, HybridLambda, Identity. Deferred shape inference matches
the reference: unknown in_units/in_channels (0) resolve at first forward.
"""

from .activations import Activation
from ..block import Block, HybridBlock, record_aux_update
from ..parameter import Parameter
from ...ndarray.ndarray import NDArray
from ...ops.registry import get_op, invoke
from ... import _tape

__all__ = ['Sequential', 'HybridSequential', 'Dense', 'Dropout', 'BatchNorm',
           'BatchNormReLU', 'SyncBatchNorm', 'LayerNorm', 'GroupNorm',
           'InstanceNorm', 'Embedding', 'Flatten', 'HybridLambda', 'Lambda',
           'Identity', 'Concatenate', 'HybridConcatenate', 'RMSNorm']


def _op(name, *args, **kw):
    return invoke(get_op(name), args, kw)


class Sequential(Block):
    """Reference basic_layers.py:Sequential."""

    def __init__(self, *blocks, **kwargs):
        super().__init__(**kwargs)
        for b in blocks:
            self.add(b)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        items = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*items[key])
            return net
        return items[key]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(Sequential, HybridBlock):
    """Reference basic_layers.py:HybridSequential."""

    def __init__(self, *blocks, **kwargs):
        HybridBlock.__init__(self, **kwargs)
        for b in blocks:
            self.add(b)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x


class Dense(HybridBlock):
    """Reference basic_layers.py:Dense → FullyConnected op
    (src/operator/nn/fully_connected.cc:251). weight: (units, in_units)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype='float32', weight_initializer=None,
                 bias_initializer='zeros', in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._use_bias = use_bias
        self.weight = Parameter('weight', shape=(units, in_units),
                                init=weight_initializer, dtype=dtype,
                                allow_deferred_init=True)
        if use_bias:
            self.bias = Parameter('bias', shape=(units,),
                                  init=bias_initializer, dtype=dtype,
                                  allow_deferred_init=True)
        self.act = Activation(activation) if activation else None

    def _infer(self, x):
        if self.weight.shape[1] == 0:
            in_units = x.size // x.shape[0] if self._flatten else x.shape[-1]
            self.weight.shape = (self._units, in_units)
            self.weight._finish_deferred_init()
        if self._use_bias and self.bias._data is None:
            self.bias._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        out = _op('fully_connected', x, self.weight.data(),
                  *([self.bias.data()] if self._use_bias else []),
                  num_hidden=self._units, no_bias=not self._use_bias,
                  flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return (f'Dense({self.weight.shape[1] or None} -> {self._units}, '
                f'{"linear" if self.act is None else self.act._act_type})')



class Dropout(HybridBlock):
    """Reference basic_layers.py:Dropout. Active only in train mode
    (autograd.is_training), as in the reference."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        if self._rate == 0:
            return x
        return _op('dropout', x, p=self._rate, axes=self._axes,
                   training=_tape.is_training())


class BatchNorm(HybridBlock):
    """Reference basic_layers.py:BatchNorm over src/operator/nn/batch_norm.cc.

    Running stats are auxiliary states updated through
    ``record_aux_update`` so they flow correctly through the compiled graph
    (extra outputs) and eagerly (direct rebind).
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer='zeros',
                 gamma_initializer='ones',
                 running_mean_initializer='zeros',
                 running_variance_initializer='ones', in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.gamma = Parameter('gamma', shape=(in_channels,),
                               init=gamma_initializer,
                               differentiable=scale,
                               allow_deferred_init=True)
        self.beta = Parameter('beta', shape=(in_channels,),
                              init=beta_initializer,
                              differentiable=center,
                              allow_deferred_init=True)
        self.running_mean = Parameter('running_mean', shape=(in_channels,),
                                      init=running_mean_initializer,
                                      grad_req='null', differentiable=False,
                                      allow_deferred_init=True)
        self.running_var = Parameter('running_var', shape=(in_channels,),
                                     init=running_variance_initializer,
                                     grad_req='null', differentiable=False,
                                     allow_deferred_init=True)

    def _infer(self, x):
        if self.gamma.shape[0] == 0:
            c = x.shape[self._axis]
            for p in (self.gamma, self.beta, self.running_mean,
                      self.running_var):
                p.shape = (c,)
                p._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        use_batch_stats = _tape.is_training() and not self._use_global_stats
        if use_batch_stats:
            out, mean, var = _op(
                'batch_norm_train', x, self.gamma.data(), self.beta.data(),
                eps=self._epsilon, axis=self._axis,
                fix_gamma=not self._scale)
            m = self._momentum
            # NDArray-level math (not raw jnp): under bulked eager the
            # blend stays inside the segment instead of flushing it at
            # every BatchNorm layer
            new_mean = self.running_mean.data() * m + \
                mean.detach() * (1 - m)
            new_var = self.running_var.data() * m + \
                var.detach() * (1 - m)
            record_aux_update(self.running_mean, new_mean)
            record_aux_update(self.running_var, new_var)
            return out
        return _op('batch_norm_inference', x, self.gamma.data(),
                   self.beta.data(), self.running_mean.data(),
                   self.running_var.data(), eps=self._epsilon,
                   axis=self._axis, fix_gamma=not self._scale)


class BatchNormReLU(BatchNorm):
    """Fused BN+ReLU (reference basic_layers.py:449 BatchNormReLU over
    _contrib_BatchNormWithReLU). On TPU the relu fuses into the BN
    elementwise epilogue inside the compiled graph — same single kernel
    the reference's hand-fused op achieves."""

    def forward(self, x):
        return _op('relu', super().forward(x))


class SyncBatchNorm(BatchNorm):
    """Cross-device BN (reference src/operator/contrib/sync_batch_norm-inl.h).

    Under pjit/shard_map the batch axis is a mesh axis and XLA's reduction
    IS global — so plain BatchNorm statistics are already synchronized when
    the model runs SPMD. This subclass exists for API parity.
    """

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)


class LayerNorm(HybridBlock):
    """Reference basic_layers.py:LayerNorm."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer='zeros', gamma_initializer='ones',
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = Parameter('gamma', shape=(in_channels,),
                               init=gamma_initializer, differentiable=scale,
                               allow_deferred_init=True)
        self.beta = Parameter('beta', shape=(in_channels,),
                              init=beta_initializer, differentiable=center,
                              allow_deferred_init=True)

    def _infer(self, x):
        if self.gamma.shape[0] == 0:
            c = x.shape[self._axis]
            for p in (self.gamma, self.beta):
                p.shape = (c,)
                p._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        return _op('layer_norm', x, self.gamma.data(), self.beta.data(),
                   axis=self._axis, eps=self._epsilon)


class RMSNorm(HybridBlock):
    """RMSNorm for the LLM stack (new over reference)."""

    def __init__(self, axis=-1, epsilon=1e-6, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = Parameter('gamma', shape=(in_channels,), init='ones',
                               allow_deferred_init=True)

    def forward(self, x):
        if self.gamma.shape[0] == 0:
            self.gamma.shape = (x.shape[self._axis],)
            self.gamma._finish_deferred_init()
        return _op('rms_norm', x, self.gamma.data(), axis=self._axis,
                   eps=self._epsilon)


class GroupNorm(HybridBlock):
    """Reference basic_layers.py:GroupNorm."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer='zeros', gamma_initializer='ones',
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = Parameter('gamma', shape=(in_channels,),
                               init=gamma_initializer, differentiable=scale,
                               allow_deferred_init=True)
        self.beta = Parameter('beta', shape=(in_channels,),
                              init=beta_initializer, differentiable=center,
                              allow_deferred_init=True)

    def forward(self, x):
        if self.gamma.shape[0] == 0:
            c = x.shape[1]
            for p in (self.gamma, self.beta):
                p.shape = (c,)
                p._finish_deferred_init()
        return _op('group_norm', x, self.gamma.data(), self.beta.data(),
                   num_groups=self._num_groups, eps=self._epsilon)


class InstanceNorm(HybridBlock):
    """Reference basic_layers.py:InstanceNorm."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer='zeros', gamma_initializer='ones',
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = Parameter('gamma', shape=(in_channels,),
                               init=gamma_initializer, differentiable=scale,
                               allow_deferred_init=True)
        self.beta = Parameter('beta', shape=(in_channels,),
                              init=beta_initializer, differentiable=center,
                              allow_deferred_init=True)

    def forward(self, x):
        if self.gamma.shape[0] == 0:
            c = x.shape[self._axis]
            for p in (self.gamma, self.beta):
                p.shape = (c,)
                p._finish_deferred_init()
        if self._axis not in (1, -x.ndim + 1):
            # channel-last (or arbitrary) layout: move channels to dim 1,
            # normalize, move back
            x_t = x.moveaxis(self._axis, 1)
            out = _op('instance_norm', x_t, self.gamma.data(),
                      self.beta.data(), eps=self._epsilon)
            return out.moveaxis(1, self._axis)
        return _op('instance_norm', x, self.gamma.data(), self.beta.data(),
                   eps=self._epsilon)


class Embedding(HybridBlock):
    """Reference basic_layers.py:Embedding → indexing_op.cc Embedding."""

    def __init__(self, input_dim, output_dim, dtype='float32',
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = Parameter(
            'weight', shape=(input_dim, output_dim),
            init=weight_initializer, dtype=dtype,
            grad_stype='row_sparse' if sparse_grad else 'default')

    def forward(self, x):
        from ... import _tape
        w = self.weight.data()
        if (self.weight._grad_stype == 'row_sparse'
                and _tape.is_recording() and _tape._needs_grad([w])):
            return _sparse_grad_embedding(x, w, self._output_dim)
        return _op('embedding', x, w,
                   input_dim=self._input_dim, output_dim=self._output_dim)


def _sparse_grad_embedding(x, w, output_dim):
    """Embedding lookup whose recorded backward emits a ROW-SPARSE
    cotangent — (per-token values, token ids) — instead of scattering
    into a dense table-shaped array (reference indexing_op.cc Embedding
    FGradient with sparse_grad: grad stype row_sparse). The dense-grad
    path is jax.vjp like every op; this path hand-writes the tape node
    because jax cotangents cannot carry sparsity."""
    import jax.numpy as jnp
    from ... import _tape
    from ...ndarray.ndarray import NDArray

    ids = x._data.astype(jnp.int32)
    out_raw = jnp.take(w._data, ids, axis=0)
    out = NDArray(out_raw)
    flat_ids = ids.reshape(-1)

    def fn(ids_raw, w_raw):     # dense replay (retain_graph fallback)
        return jnp.take(w_raw, ids_raw.astype(jnp.int32), axis=0)

    def vjp(cot):
        vals = cot.reshape(flat_ids.shape[0], -1)
        return (None,      # integer ids: no gradient
                _tape.RowSparseCot(vals, flat_ids, w.shape))

    import jax
    node = _tape.TapeNode(
        fn, [ids, w._data],
        [getattr(x, '_ag', None), getattr(w, '_ag', None)],
        1, 'embedding_sparse_grad', vjp_fn=vjp,
        out_avals=[jax.typeof(out_raw)], multi=False)
    out._ag = _tape.AGInfo(node=node, index=0)
    return out


class Flatten(HybridBlock):
    def forward(self, x):
        return _op('flatten', x)

    def __repr__(self):
        return 'Flatten'


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Lambda(Block):
    """Reference basic_layers.py:Lambda."""

    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        if isinstance(function, str):
            from ... import ndarray as F
            function = getattr(F, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        if isinstance(function, str):
            from ... import ndarray as F
            function = getattr(F, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class Concatenate(Block):
    """Run children on the same input, concat outputs (reference
    basic_layers.py:Concatenate)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def add(self, block):
        self.register_child(block)

    def forward(self, x):
        outs = [block(x) for block in self._children.values()]
        return _op('concatenate', *outs, axis=self.axis)


class HybridConcatenate(HybridBlock):
    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def add(self, block):
        self.register_child(block)

    def forward(self, x):
        outs = [block(x) for block in self._children.values()]
        return _op('concatenate', *outs, axis=self.axis)
