"""Convolution + pooling layers (reference
python/mxnet/gluon/nn/conv_layers.py: Conv1D-3D, Conv*DTranspose,
MaxPool/AvgPool/GlobalPool 1-3D, ReflectionPad2D).
"""

from .activations import Activation
from ..block import HybridBlock
from ..parameter import Parameter
from ...ops.registry import get_op, invoke

__all__ = ['Conv1D', 'Conv2D', 'Conv3D', 'Conv1DTranspose',
           'Conv2DTranspose', 'Conv3DTranspose', 'MaxPool1D', 'MaxPool2D',
           'MaxPool3D', 'AvgPool1D', 'AvgPool2D', 'AvgPool3D',
           'GlobalMaxPool1D', 'GlobalMaxPool2D', 'GlobalMaxPool3D',
           'GlobalAvgPool1D', 'GlobalAvgPool2D', 'GlobalAvgPool3D',
           'ReflectionPad2D']


def _op(name, *args, **kw):
    return invoke(get_op(name), args, kw)


def _pair(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    """Base conv (reference conv_layers.py:_Conv). Weight layout OIHW, data
    NCHW by default (API parity); the op lowers to one MXU
    conv_general_dilated either way."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer='zeros', op_name='convolution',
                 adj=None, output_padding=None, **kwargs):
        super().__init__(**kwargs)
        ndim = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._strides = strides
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._layout = layout
        self._use_bias = use_bias
        self._op_name = op_name
        self._adj = adj
        if op_name == 'convolution':
            wshape = (channels, in_channels // groups if in_channels else 0)\
                + kernel_size
        else:  # transposed: (in, out//groups, *k)
            wshape = (in_channels if in_channels else 0,
                      channels // groups) + kernel_size
        self.weight = Parameter('weight', shape=wshape,
                                init=weight_initializer,
                                allow_deferred_init=True)
        if use_bias:
            self.bias = Parameter('bias', shape=(channels,),
                                  init=bias_initializer,
                                  allow_deferred_init=True)
        self.act = Activation(activation) if activation else None

    def _infer(self, x):
        c_axis = self._layout.index('C')
        in_c = x.shape[c_axis]
        w = list(self.weight.shape)
        if self._op_name == 'convolution' and w[1] == 0:
            w[1] = in_c // self._groups
            self.weight.shape = tuple(w)
            self.weight._finish_deferred_init()
        elif self._op_name == 'deconvolution' and w[0] == 0:
            w[0] = in_c
            self.weight.shape = tuple(w)
            self.weight._finish_deferred_init()
        if self._use_bias and self.bias._data is None:
            self.bias._finish_deferred_init()

    def forward(self, x):
        self._infer(x)
        kwargs = dict(kernel=self._kernel, stride=self._strides,
                      dilate=self._dilation, pad=self._padding,
                      num_filter=self._channels, num_group=self._groups,
                      no_bias=not self._use_bias, layout=self._layout)
        if self._op_name == 'deconvolution':
            kwargs['adj'] = self._adj
        args = [x, self.weight.data()]
        if self._use_bias:
            args.append(self.bias.data())
        out = _op(self._op_name, *args, **kwargs)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return (f'{type(self).__name__}({self._channels}, '
                f'kernel_size={self._kernel}, stride={self._strides})')


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout='NCW', **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups,
                         layout, **kwargs)


class Conv2D(_Conv):
    """Reference conv_layers.py:Conv2D."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout='NCHW', **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups,
                         layout, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout='NCDHW', **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups,
                         layout, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout='NCW',
                 **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups,
                         layout, op_name='deconvolution',
                         adj=_pair(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout='NCHW', **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups,
                         layout, op_name='deconvolution',
                         adj=_pair(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout='NCDHW', **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups,
                         layout, op_name='deconvolution',
                         adj=_pair(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=True, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = dict(
            kernel=pool_size, stride=strides or pool_size, pad=padding,
            pool_type=pool_type, global_pool=global_pool,
            pooling_convention='full' if ceil_mode else 'valid',
            count_include_pad=count_include_pad, layout=layout)

    def forward(self, x):
        return _op('pooling', x, **self._kwargs)

    def __repr__(self):
        return (f'{type(self).__name__}(size={self._kwargs["kernel"]}, '
                f'stride={self._kwargs["stride"]})')


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout='NCW',
                 ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 1),
                         _pair(strides, 1) if strides else None,
                         _pair(padding, 1), ceil_mode, False, 'max', layout,
                         **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout='NCHW', ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 2),
                         _pair(strides, 2) if strides else None,
                         _pair(padding, 2), ceil_mode, False, 'max', layout,
                         **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout='NCDHW', ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 3),
                         _pair(strides, 3) if strides else None,
                         _pair(padding, 3), ceil_mode, False, 'max', layout,
                         **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout='NCW',
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_pair(pool_size, 1),
                         _pair(strides, 1) if strides else None,
                         _pair(padding, 1), ceil_mode, False, 'avg', layout,
                         count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout='NCHW', ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_pair(pool_size, 2),
                         _pair(strides, 2) if strides else None,
                         _pair(padding, 2), ceil_mode, False, 'avg', layout,
                         count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout='NCDHW', ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_pair(pool_size, 3),
                         _pair(strides, 3) if strides else None,
                         _pair(padding, 3), ceil_mode, False, 'avg', layout,
                         count_include_pad, **kwargs)


class _GlobalPool(_Pooling):
    def __init__(self, pool_type, layout, **kwargs):
        ndim = len(layout) - 2
        super().__init__((1,) * ndim, (1,) * ndim, (0,) * ndim, False, True,
                         pool_type, layout, **kwargs)


class GlobalMaxPool1D(_GlobalPool):
    def __init__(self, layout='NCW', **kw):
        super().__init__('max', layout, **kw)


class GlobalMaxPool2D(_GlobalPool):
    def __init__(self, layout='NCHW', **kw):
        super().__init__('max', layout, **kw)


class GlobalMaxPool3D(_GlobalPool):
    def __init__(self, layout='NCDHW', **kw):
        super().__init__('max', layout, **kw)


class GlobalAvgPool1D(_GlobalPool):
    def __init__(self, layout='NCW', **kw):
        super().__init__('avg', layout, **kw)


class GlobalAvgPool2D(_GlobalPool):
    def __init__(self, layout='NCHW', **kw):
        super().__init__('avg', layout, **kw)


class GlobalAvgPool3D(_GlobalPool):
    def __init__(self, layout='NCDHW', **kw):
        super().__init__('avg', layout, **kw)


class ReflectionPad2D(HybridBlock):
    """Reference conv_layers.py:ReflectionPad2D."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            p = (padding,) * 4
        else:
            p = tuple(padding)
        if len(p) == 8:
            # reference 8-tuple (N, C, H, W begin/end pairs)
            self._pad = ((p[0], p[1]), (p[2], p[3]), (p[4], p[5]),
                         (p[6], p[7]))
        elif len(p) == 4:
            self._pad = ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3]))
        else:
            raise ValueError(f'padding must be int, 4- or 8-tuple, got '
                             f'{padding!r}')

    def forward(self, x):
        return _op('pad', x, pad_width=self._pad, mode='reflect')
