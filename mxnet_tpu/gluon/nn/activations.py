"""Activation layers (reference python/mxnet/gluon/nn/activations.py)."""

from ..block import HybridBlock
from ..parameter import Parameter
from ...ops.registry import get_op, invoke


def _op(name, x, **kw):
    return invoke(get_op(name), (x,), kw)


class Activation(HybridBlock):
    """Generic activation (reference activations.py:Activation)."""

    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act_type = activation

    def forward(self, x):
        return _op('activation', x, act_type=self._act_type)

    def __repr__(self):
        return f'Activation({self._act_type})'


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return _op('leaky_relu', x, act_type='leaky', slope=self._alpha)


class PReLU(HybridBlock):
    """Reference activations.py:PReLU (learned negative slope)."""

    def __init__(self, alpha_initializer='zeros', in_channels=1, **kwargs):
        super().__init__(**kwargs)
        self.alpha = Parameter('alpha', shape=(in_channels,),
                               init=alpha_initializer)

    def forward(self, x):
        return _op('leaky_relu', x, gamma=self.alpha.data(),
                   act_type='prelu')


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return _op('leaky_relu', x, act_type='elu', slope=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return _op('leaky_relu', x, act_type='selu')


class GELU(HybridBlock):
    def __init__(self, approximation='erf', **kwargs):
        super().__init__(**kwargs)
        self._approx = approximation != 'erf'

    def forward(self, x):
        return _op('gelu', x, approximate=self._approx)


class SiLU(HybridBlock):
    def forward(self, x):
        return _op('silu', x)


Swish = SiLU
