"""SSD single-shot detector.

Reference assets: the SSD multibox op family
(``src/operator/contrib/multibox_prior.cc`` / ``multibox_target.cc`` /
``multibox_detection.cc``) + the SSD example
(``example/ssd`` in the reference era; GluonCV ``ssd_300_*`` models).
TPU design: every stage — backbone, multi-scale heads, anchor
generation (constant-folded), box decode and per-class NMS — is one
static-shape compiled graph; training mode returns raw predictions +
anchors for ``multibox_target``.
"""

import numpy as _np

from ... import _tape
from ... import np as mnp
from .. import nn
from ..block import HybridBlock
from .yolo import _op


def _conv_block(channels, kernel, stride=1, pad=0):
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(channels, kernel, strides=stride, padding=pad,
                      use_bias=False),
            nn.BatchNorm(), nn.Activation('relu'))
    return blk


class _SSDFeatures(HybridBlock):
    """Truncated backbone + stride-2 extra blocks → multi-scale maps.

    Uses the resnet18 feature trunk (stages to stride 16 and 32) and
    ``num_extra`` additional downsampling blocks — the role of the
    reference's VGG-atrous + extra layers."""

    def __init__(self, num_extra=2, **kwargs):
        super().__init__(**kwargs)
        from .vision import resnet18_v1
        base = resnet18_v1()
        feats = list(base.features._children.values())
        # stages: conv..stage3 (stride 16) | stage4 (stride 32)
        self.stage1 = nn.HybridSequential()
        for layer in feats[:7]:
            self.stage1.add(layer)
        self.stage2 = nn.HybridSequential()
        self.stage2.add(feats[7])
        self.extras = nn.HybridSequential()
        for _ in range(num_extra):
            blk = nn.HybridSequential()
            blk.add(_conv_block(256, 1),
                    _conv_block(512, 3, stride=2, pad=1))
            self.extras.add(blk)

    def forward(self, x):
        outs = []
        x = self.stage1(x)
        outs.append(x)                    # stride 16
        x = self.stage2(x)
        outs.append(x)                    # stride 32
        for blk in self._children['extras']._children.values():
            x = blk(x)
            outs.append(x)                # stride 64, 128, ...
        return outs


class SSD(HybridBlock):
    """Single-shot detector over multi-scale feature maps.

    ``forward(x)``:
      * training (autograd recording): ``(cls_preds (N, A, C+1),
        loc_preds (N, A*4), anchors (1, A, 4))`` — feed to
        ``mx.npx.multibox_target`` for loss targets;
      * inference: ``(ids, scores, boxes)`` via ``multibox_detection``
        (+ per-class NMS), all inside the compiled graph. Anchors are
        in [0, 1] normalized corners (reference convention).
    """

    def __init__(self, classes=20, sizes=None, ratios=None, num_extra=2,
                 nms_thresh=0.45, nms_topk=100, post_nms=100, **kwargs):
        super().__init__(**kwargs)
        n_scales = 2 + num_extra
        if sizes is None:
            # linearly spaced scales, paired with the next scale's
            # geometric mean (the reference SSD sizing rule)
            lo, hi = 0.2, 0.9
            s = _np.linspace(lo, hi, n_scales + 1)
            sizes = [(float(s[i]), float(_np.sqrt(s[i] * s[i + 1])))
                     for i in range(n_scales)]
        if ratios is None:
            ratios = [(1.0, 2.0, 0.5)] * n_scales
        assert len(sizes) == len(ratios) == n_scales
        self._classes = classes
        self._sizes = sizes
        self._ratios = ratios
        self._nms_thresh = nms_thresh
        self._nms_topk = nms_topk
        self._post_nms = post_nms
        self.features = _SSDFeatures(num_extra=num_extra)
        self.class_preds = nn.HybridSequential()
        self.box_preds = nn.HybridSequential()
        for sz, rt in zip(sizes, ratios):
            a = len(sz) + len(rt) - 1
            self.class_preds.add(nn.Conv2D(a * (classes + 1), 3,
                                           padding=1))
            self.box_preds.add(nn.Conv2D(a * 4, 3, padding=1))

    def forward(self, x):
        feats = self.features(x)
        cls_preds, loc_preds, anchors = [], [], []
        for i, feat in enumerate(feats):
            cp = self.class_preds[i](feat)       # (N, A*(C+1), H, W)
            bp = self.box_preds[i](feat)         # (N, A*4, H, W)
            N, _, H, W = cp.shape
            a = len(self._sizes[i]) + len(self._ratios[i]) - 1
            cls_preds.append(
                cp.transpose(0, 2, 3, 1).reshape(
                    N, H * W * a, self._classes + 1))
            loc_preds.append(
                bp.transpose(0, 2, 3, 1).reshape(N, H * W * a * 4))
            anchors.append(_op('multibox_prior', feat,
                               sizes=self._sizes[i],
                               ratios=self._ratios[i], clip=True))
        cls_pred = _op('concatenate', cls_preds, axis=1)  # (N, A, C+1)
        loc_pred = _op('concatenate', loc_preds, axis=1)  # (N, A*4)
        anchor = _op('concatenate', anchors, axis=1)      # (1, A, 4)

        # is_training (not is_recording): inside a hybridized trace the
        # recorder is off but the train flag carries through, so the
        # training branch compiles correctly under hybridize too
        if _tape.is_training():
            return cls_pred, loc_pred, anchor

        cls_prob = _op('softmax', cls_pred, axis=-1)
        cls_prob = cls_prob.transpose(0, 2, 1)            # (N, C+1, A)
        dets = _op('multibox_detection', cls_prob, loc_pred, anchor,
                   nms_threshold=self._nms_thresh,
                   nms_topk=self._nms_topk)               # (N, A, 6)
        # fixed-size output: top post_nms by score (clamped to the
        # anchor count — small inputs/configs can have A < post_nms)
        scores = dets[:, :, 1]
        k = min(self._post_nms, int(scores.shape[1]))
        idx = _op('topk', scores, axis=1, k=k,
                  ret_typ='indices', is_ascend=False, dtype='int32')
        top = _op('take_along_axis', dets,
                  mnp.expand_dims(idx, -1).astype('int32'), 1)
        return top[:, :, 0], top[:, :, 1], top[:, :, 2:]


def ssd_300_resnet18_v1(classes=20, **kwargs):
    """SSD-300-class model over the resnet18 trunk (reference
    example/ssd ssd_300 config; GluonCV naming convention)."""
    return SSD(classes=classes, **kwargs)
