"""Inception V3 (reference
python/mxnet/gluon/model_zoo/vision/inception.py)."""

from ...block import HybridBlock
from ...nn import (Activation, AvgPool2D, BatchNorm, Conv2D, Dense, Dropout,
                   Flatten, GlobalAvgPool2D, HybridConcatenate,
                   HybridSequential, MaxPool2D)

__all__ = ['Inception3', 'inception_v3']


def _make_basic_conv(**kwargs):
    out = HybridSequential()
    out.add(Conv2D(use_bias=False, **kwargs))
    out.add(BatchNorm(epsilon=0.001))
    out.add(Activation('relu'))
    return out


def _make_branch(use_pool, *conv_settings):
    out = HybridSequential()
    if use_pool == 'avg':
        out.add(AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == 'max':
        out.add(MaxPool2D(pool_size=3, strides=2))
    for setting in conv_settings:
        kwargs = {}
        for key, value in zip(['channels', 'kernel_size', 'strides',
                               'padding'], setting):
            if value is not None:
                kwargs[key] = value
        out.add(_make_basic_conv(**kwargs))
    return out


def _concat(*branches):
    c = HybridConcatenate(axis=1)
    for b in branches:
        c.add(b)
    return c


def _make_A(pool_features):
    return _concat(
        _make_branch(None, (64, 1, None, None)),
        _make_branch(None, (48, 1, None, None), (64, 5, None, 2)),
        _make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                     (96, 3, None, 1)),
        _make_branch('avg', (pool_features, 1, None, None)))


def _make_B():
    return _concat(
        _make_branch(None, (384, 3, 2, None)),
        _make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                     (96, 3, 2, None)),
        _make_branch('max'))


def _make_C(channels_7x7):
    return _concat(
        _make_branch(None, (192, 1, None, None)),
        _make_branch(None, (channels_7x7, 1, None, None),
                     (channels_7x7, (1, 7), None, (0, 3)),
                     (192, (7, 1), None, (3, 0))),
        _make_branch(None, (channels_7x7, 1, None, None),
                     (channels_7x7, (7, 1), None, (3, 0)),
                     (channels_7x7, (1, 7), None, (0, 3)),
                     (channels_7x7, (7, 1), None, (3, 0)),
                     (192, (1, 7), None, (0, 3))),
        _make_branch('avg', (192, 1, None, None)))


def _make_D():
    return _concat(
        _make_branch(None, (192, 1, None, None), (320, 3, 2, None)),
        _make_branch(None, (192, 1, None, None),
                     (192, (1, 7), None, (0, 3)),
                     (192, (7, 1), None, (3, 0)), (192, 3, 2, None)),
        _make_branch('max'))


class _InceptionE(HybridBlock):
    """E block needs a nested concat, so it's a Block (reference uses the
    same trick via nested Concurrent)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.branch1 = _make_branch(None, (320, 1, None, None))
        self.branch2_stem = _make_basic_conv(channels=384, kernel_size=1)
        self.branch2_a = _make_basic_conv(channels=384, kernel_size=(1, 3),
                                          padding=(0, 1))
        self.branch2_b = _make_basic_conv(channels=384, kernel_size=(3, 1),
                                          padding=(1, 0))
        self.branch3_stem = _make_branch(None, (448, 1, None, None),
                                         (384, 3, None, 1))
        self.branch3_a = _make_basic_conv(channels=384, kernel_size=(1, 3),
                                          padding=(0, 1))
        self.branch3_b = _make_basic_conv(channels=384, kernel_size=(3, 1),
                                          padding=(1, 0))
        self.branch4 = _make_branch('avg', (192, 1, None, None))

    def forward(self, x):
        from ....ops.registry import get_op, invoke
        cat = lambda *xs: invoke(get_op('concatenate'), xs, {'axis': 1})
        b1 = self.branch1(x)
        b2 = self.branch2_stem(x)
        b2 = cat(self.branch2_a(b2), self.branch2_b(b2))
        b3 = self.branch3_stem(x)
        b3 = cat(self.branch3_a(b3), self.branch3_b(b3))
        b4 = self.branch4(x)
        return cat(b1, b2, b3, b4)


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = HybridSequential()
        self.features.add(_make_basic_conv(channels=32, kernel_size=3,
                                           strides=2))
        self.features.add(_make_basic_conv(channels=32, kernel_size=3))
        self.features.add(_make_basic_conv(channels=64, kernel_size=3,
                                           padding=1))
        self.features.add(MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_basic_conv(channels=80, kernel_size=1))
        self.features.add(_make_basic_conv(channels=192, kernel_size=3))
        self.features.add(MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_InceptionE())
        self.features.add(_InceptionE())
        self.features.add(AvgPool2D(pool_size=8))
        self.features.add(Dropout(0.5))
        self.output = Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, ctx=None, root=None, **kwargs):
    from ..model_store import apply_pretrained
    return apply_pretrained(Inception3(**kwargs), pretrained,
                            'inceptionv3', ctx, root)
