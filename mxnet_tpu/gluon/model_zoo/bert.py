"""BERT model family.

The reference repo ships no BERT (GluonNLP was a separate project;
SURVEY §6 notes BERT-base samples/sec must be established fresh as a
north-star metric). This implementation is TPU-first:

* attention runs through ``npx.multi_head_attention`` → the Pallas flash
  path (ops/pallas/flash_attention.py) when unmasked/causal, or the
  XLA-fused masked path for padded batches;
* GELU/LayerNorm/bias adds are left to XLA fusion (the role of the
  reference's NVRTC pointwise fusion, src/operator/fusion/);
* everything is a HybridBlock, so one ``hybridize()`` compiles the whole
  encoder into a single XLA executable with donated buffers.

API shape follows gluon model_zoo conventions: ``bert_12_768_12`` /
``bert_24_1024_16`` constructors plus a ``get_bert_model`` factory.
"""

import math

from ...context import current_context
from ..block import HybridBlock
from ..parameter import Parameter
from .. import nn
from ... import initializer


class BERTLayerNorm(nn.LayerNorm):
    """LayerNorm with BERT's default epsilon."""

    def __init__(self, in_channels=0, epsilon=1e-12, **kwargs):
        super().__init__(epsilon=epsilon, in_channels=in_channels, **kwargs)


class BERTSelfAttention(HybridBlock):
    """Multi-head self-attention; QKV in one fused projection (one MXU
    matmul instead of three — the TPU equivalent of the reference's
    interleaved QKV layout, transformer.cc:650)."""

    def __init__(self, units, num_heads, dropout=0.0):
        super().__init__()
        self._units = units
        self._num_heads = num_heads
        self.qkv = nn.Dense(3 * units, flatten=False)
        self.proj = nn.Dense(units, flatten=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x, mask=None):
        from ... import npx
        qkv = self.qkv(x)
        q, k, v = npx.split(qkv, 3, axis=-1)
        out = npx.multi_head_attention(q, k, v, self._num_heads, mask=mask)
        out = self.proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class BERTEncoderCell(HybridBlock):
    """Post-LN transformer encoder cell (attention → add&norm → FFN →
    add&norm), the original BERT arrangement."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0):
        super().__init__()
        self.attention = BERTSelfAttention(units, num_heads, dropout)
        self.ln1 = BERTLayerNorm(in_channels=units)
        self.ffn1 = nn.Dense(hidden_size, flatten=False)
        self.act = nn.GELU()
        self.ffn2 = nn.Dense(units, flatten=False)
        self.ln2 = BERTLayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x, mask=None):
        att = self.attention(x, mask)
        x = self.ln1(x + att)
        h = self.ffn2(self.act(self.ffn1(x)))
        if self.dropout is not None:
            h = self.dropout(h)
        return self.ln2(x + h)


class BERTEncoder(HybridBlock):
    """Stack of encoder cells. Position embeddings live in
    :class:`BERTModel` (added before the embedding LayerNorm, as BERT
    specifies)."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 max_length=512, dropout=0.0):
        super().__init__()
        self._max_length = max_length
        self._units = units
        self.dropout = nn.Dropout(dropout) if dropout else None
        self.cells = []
        for i in range(num_layers):
            cell = BERTEncoderCell(units, hidden_size, num_heads, dropout)
            self.register_child(cell, f'cell{i}')
            self.cells.append(cell)

    def forward(self, x, mask=None):
        if self.dropout is not None:
            x = self.dropout(x)
        for cell in self.cells:
            x = cell(x, mask)
        return x


class BERTModel(HybridBlock):
    """BERT with MLM + NSP heads (reference-free TPU design; API follows
    gluon model_zoo conventions).

    Inputs: ``token_ids (B, T)``, ``token_types (B, T)``, optional
    ``valid_length (B,)``. Outputs: sequence encoding (B, T, U); with
    ``use_decoder`` also MLM logits (B, T, vocab); with ``use_classifier``
    also NSP logits (B, 2).
    """

    def __init__(self, vocab_size=30522, token_type_vocab_size=2,
                 units=768, hidden_size=3072, num_layers=12, num_heads=12,
                 max_length=512, dropout=0.1, use_pooler=True,
                 use_decoder=True, use_classifier=True, **kwargs):
        super().__init__()
        self._units = units
        self.word_embed = nn.Embedding(vocab_size, units)
        self.token_type_embed = nn.Embedding(token_type_vocab_size, units)
        self.position_weight = Parameter(
            'position_weight', shape=(max_length, units),
            init=initializer.Normal(0.02))
        self.embed_ln = BERTLayerNorm(in_channels=units)
        self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                   num_heads, max_length, dropout)
        if use_classifier and not use_pooler:
            raise ValueError(
                'use_classifier=True requires use_pooler=True (NSP head '
                'classifies the pooled [CLS] representation)')
        self.use_pooler = use_pooler
        self.use_decoder = use_decoder
        self.use_classifier = use_classifier
        if use_pooler:
            self.pooler = nn.Dense(units, activation='tanh', flatten=False)
        if use_decoder:
            # MLM head ties the output projection to the word embedding
            self.decoder_transform = nn.Dense(units, flatten=False)
            self.decoder_act = nn.GELU()
            self.decoder_ln = BERTLayerNorm(in_channels=units)
            self.decoder_bias = Parameter(
                'decoder_bias', shape=(vocab_size,),
                init=initializer.Zero())
        if use_classifier:
            self.classifier = nn.Dense(2, flatten=False)

    def _attention_mask(self, token_ids, valid_length):
        from ... import np as mnp
        if valid_length is None:
            return None
        t = token_ids.shape[1]
        pos = mnp.arange(t).reshape(1, t)
        valid = pos < mnp.expand_dims(valid_length, -1)   # (B, T)
        # (B, 1, Tq, Tk) boolean mask for dot_product_attention
        return mnp.expand_dims(mnp.expand_dims(valid, 1), 1)

    def forward(self, token_ids, token_types=None, valid_length=None):
        from ... import np as mnp
        x = self.word_embed(token_ids)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        # position added BEFORE the embedding LayerNorm (BERT spec; the
        # HF-parity test pins this ordering)
        pos = self.position_weight.data()[:token_ids.shape[1]]
        x = x + mnp.expand_dims(pos, 0)
        x = self.embed_ln(x)
        mask = self._attention_mask(token_ids, valid_length)
        seq = self.encoder(x, mask)
        outputs = [seq]
        if self.use_pooler:
            pooled = self.pooler(seq[:, 0, :])
            outputs.append(pooled)
        if self.use_decoder:
            h = self.decoder_ln(self.decoder_act(self.decoder_transform(seq)))
            # tied projection: logits = h · E^T + b
            emb = self.word_embed.weight.data()
            logits = mnp.matmul(h, emb.T) + self.decoder_bias.data()
            outputs.append(logits)
        if self.use_classifier and self.use_pooler:
            outputs.append(self.classifier(pooled))
        return tuple(outputs) if len(outputs) > 1 else outputs[0]


_BERT_CONFIGS = {
    'bert_12_768_12': dict(units=768, hidden_size=3072, num_layers=12,
                           num_heads=12),
    'bert_24_1024_16': dict(units=1024, hidden_size=4096, num_layers=24,
                            num_heads=16),
}


def get_bert_model(model_name='bert_12_768_12', vocab_size=30522,
                   max_length=512, dropout=0.1, **kwargs):
    cfg = dict(_BERT_CONFIGS[model_name])
    cfg.update(kwargs)
    return BERTModel(vocab_size=vocab_size, max_length=max_length,
                     dropout=dropout, **cfg)


def bert_12_768_12(**kwargs):
    """BERT-base (110M params)."""
    return get_bert_model('bert_12_768_12', **kwargs)


def bert_24_1024_16(**kwargs):
    """BERT-large (340M params)."""
    return get_bert_model('bert_24_1024_16', **kwargs)


def load_hf_state_dict(net, state_dict):
    """Load HuggingFace-Transformers BERT weights into an initialized
    :class:`BERTModel` (local weights only; the pretrained-load surface ≙
    model_store.py). HF's separate query/key/value projections concatenate
    into the fused ``qkv`` kernel; MLM/NSP heads map when the model was
    built with them."""
    import numpy as _np

    def to_np(v):
        if hasattr(v, 'detach'):
            v = v.detach().cpu().float().numpy()
        return _np.asarray(v, _np.float32)

    sd = {}
    for k, v in state_dict.items():
        if k.startswith('bert.'):
            k = k[len('bert.'):]
        sd[k] = to_np(v)

    params = net.collect_params()

    def put(name, value):
        p = params[name]
        if tuple(p.shape) != value.shape:
            raise ValueError(f'{name}: {value.shape} vs {tuple(p.shape)}')
        p.set_data(value)

    put('word_embed.weight', sd['embeddings.word_embeddings.weight'])
    put('token_type_embed.weight',
        sd['embeddings.token_type_embeddings.weight'])
    pos = sd['embeddings.position_embeddings.weight']
    put('position_weight', pos[:params['position_weight'].shape[0]])
    put('embed_ln.gamma', sd['embeddings.LayerNorm.weight'])
    put('embed_ln.beta', sd['embeddings.LayerNorm.bias'])

    n_layers = len(net.encoder.cells)
    for i in range(n_layers):
        hf = f'encoder.layer.{i}.'
        ours = f'encoder.cell{i}.'
        qkv_w = _np.concatenate([sd[hf + 'attention.self.query.weight'],
                                 sd[hf + 'attention.self.key.weight'],
                                 sd[hf + 'attention.self.value.weight']], 0)
        qkv_b = _np.concatenate([sd[hf + 'attention.self.query.bias'],
                                 sd[hf + 'attention.self.key.bias'],
                                 sd[hf + 'attention.self.value.bias']], 0)
        put(ours + 'attention.qkv.weight', qkv_w)
        put(ours + 'attention.qkv.bias', qkv_b)
        put(ours + 'attention.proj.weight',
            sd[hf + 'attention.output.dense.weight'])
        put(ours + 'attention.proj.bias',
            sd[hf + 'attention.output.dense.bias'])
        put(ours + 'ln1.gamma', sd[hf + 'attention.output.LayerNorm.weight'])
        put(ours + 'ln1.beta', sd[hf + 'attention.output.LayerNorm.bias'])
        put(ours + 'ffn1.weight', sd[hf + 'intermediate.dense.weight'])
        put(ours + 'ffn1.bias', sd[hf + 'intermediate.dense.bias'])
        put(ours + 'ffn2.weight', sd[hf + 'output.dense.weight'])
        put(ours + 'ffn2.bias', sd[hf + 'output.dense.bias'])
        put(ours + 'ln2.gamma', sd[hf + 'output.LayerNorm.weight'])
        put(ours + 'ln2.beta', sd[hf + 'output.LayerNorm.bias'])

    if net.use_pooler and 'pooler.dense.weight' in sd:
        put('pooler.weight', sd['pooler.dense.weight'])
        put('pooler.bias', sd['pooler.dense.bias'])
    if net.use_decoder and 'cls.predictions.transform.dense.weight' in sd:
        put('decoder_transform.weight',
            sd['cls.predictions.transform.dense.weight'])
        put('decoder_transform.bias',
            sd['cls.predictions.transform.dense.bias'])
        put('decoder_ln.gamma',
            sd['cls.predictions.transform.LayerNorm.weight'])
        put('decoder_ln.beta',
            sd['cls.predictions.transform.LayerNorm.bias'])
        put('decoder_bias', sd['cls.predictions.bias'])
    if net.use_classifier and 'cls.seq_relationship.weight' in sd:
        put('classifier.weight', sd['cls.seq_relationship.weight'])
        put('classifier.bias', sd['cls.seq_relationship.bias'])
    return net
