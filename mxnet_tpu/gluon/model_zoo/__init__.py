"""``gluon.model_zoo`` (reference python/mxnet/gluon/model_zoo/).

``vision`` mirrors the reference zoo; ``bert`` adds the transformer family
(the reference kept BERT in the separate GluonNLP repo — SURVEY §6)."""

from . import vision
from . import bert
from . import llama
from .vision import get_model
from .bert import BERTModel, bert_12_768_12, bert_24_1024_16, get_bert_model
from .llama import (LlamaConfig, LlamaForCausalLM, llama_tiny, llama2_7b,
                    llama3_8b, get_llama, llama_partition_rules)
from .yolo import Darknet53, YOLOv3, darknet53, yolo3_darknet53
from .transformer import TransformerMT, transformer_base_mt
from .rcnn import FasterRCNN, faster_rcnn_resnet50_v1
from .ssd import SSD, ssd_300_resnet18_v1
