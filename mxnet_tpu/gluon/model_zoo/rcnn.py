"""Faster R-CNN with a ResNet-50 C4 backbone.

Reference: the BASELINE.json "GluonCV: Faster-RCNN" config over the
reference repo's detection operators — RPN proposals
(src/operator/contrib/proposal.cc) and ROIAlign
(src/operator/contrib/roi_align.cc). TPU re-design: the proposal op is
already static-shape (fixed post-NMS count), ROI pooling is a batched
bilinear gather, and the per-ROI head is a dense stack — so the whole
inference path is one compiled graph of fixed shapes; no dynamic box
counts anywhere (the reference pads/copies on the fly instead).
"""

import numpy as _np

from .. import nn
from ..block import HybridBlock
from .vision import resnet50_v1
from .yolo import _op, nms_detection_output

__all__ = ['FasterRCNN', 'faster_rcnn_resnet50_v1']


# bbox regression normalization (GluonCV/Detectron convention)
_BOX_STDS = (0.1, 0.1, 0.2, 0.2)


class RPN(HybridBlock):
    """Region proposal network head: 3x3 conv + 1x1 objectness/regression."""

    def __init__(self, channels=512, num_anchors=9, **kwargs):
        super().__init__(**kwargs)
        self._num_anchors = num_anchors
        self.conv = nn.Conv2D(channels, kernel_size=3, padding=1,
                              activation='relu')
        self.cls = nn.Conv2D(2 * num_anchors, kernel_size=1)
        self.reg = nn.Conv2D(4 * num_anchors, kernel_size=1)

    def forward(self, feat):
        from ... import npx
        h = self.conv(feat)
        raw_cls = self.cls(h)                     # (N, 2A, H, W)
        reg = self.reg(h)                         # (N, 4A, H, W)
        N, _, H, W = raw_cls.shape
        A = self._num_anchors
        prob = npx.softmax(
            raw_cls.reshape(N, 2, A, H, W), axis=1).reshape(N, 2 * A, H, W)
        return raw_cls, prob, reg


class FasterRCNN(HybridBlock):
    """Two-stage detector: RPN proposals → ROIAlign → 2-FC head.

    Inference returns ``(ids, scores, boxes)`` with a fixed candidate
    axis of ``min(post_nms * classes, pre_nms)`` entries: the raw
    per-class candidates are first cut to the ``pre_nms`` best by score
    (one top-k, keeps the quadratic NMS IoU matrix HBM-sized) before
    per-class NMS keeps ``nms_topk`` each. Training mode (autograd
    recording) returns the raw stage outputs for the loss:
    ``(rpn_cls_raw, rpn_reg, cls_scores, bbox_deltas, rois)``.
    """

    def __init__(self, classes=20, rpn_channels=512, post_nms=128,
                 scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                 nms_thresh=0.5, nms_topk=100, roi_size=7, pre_nms=400,
                 **kwargs):
        super().__init__(**kwargs)
        self._pre_nms = pre_nms
        self._classes = classes
        self._post_nms = post_nms
        self._scales = scales
        self._ratios = ratios
        self._nms_thresh = nms_thresh
        self._nms_topk = nms_topk
        self._roi_size = roi_size
        base = resnet50_v1()
        self.features = nn.HybridSequential()
        for layer in list(base.features._children.values())[:7]:
            self.features.add(layer)              # C4: stride 16, 1024ch
        self.rpn = RPN(rpn_channels, len(scales) * len(ratios))
        self.head = nn.HybridSequential()
        self.head.add(nn.Dense(1024, flatten=True, activation='relu'))
        self.head.add(nn.Dense(1024, activation='relu'))
        self.cls_pred = nn.Dense(classes + 1)     # + background
        self.box_pred = nn.Dense(4 * classes)

    def _decode_boxes(self, rois, deltas, im_h, im_w):
        """Apply per-class deltas to ROI boxes and clip to image bounds
        (corner in → corner out; GluonCV BBoxClipToImage parity)."""
        from ... import np as mnp
        x1, y1, x2, y2 = (rois[:, 1], rois[:, 2], rois[:, 3], rois[:, 4])
        w = mnp.maximum(x2 - x1, 1.0)
        h = mnp.maximum(y2 - y1, 1.0)
        cx = x1 + 0.5 * w
        cy = y1 + 0.5 * h
        d = deltas.reshape(deltas.shape[0], self._classes, 4)
        dx = d[:, :, 0] * _BOX_STDS[0]
        dy = d[:, :, 1] * _BOX_STDS[1]
        dw = mnp.clip(d[:, :, 2] * _BOX_STDS[2], -10.0, 4.0)
        dh = mnp.clip(d[:, :, 3] * _BOX_STDS[3], -10.0, 4.0)
        ncx = cx[:, None] + dx * w[:, None]
        ncy = cy[:, None] + dy * h[:, None]
        nw = w[:, None] * _op('exp', dw)
        nh = h[:, None] * _op('exp', dh)
        bx1 = mnp.clip(ncx - nw / 2, 0.0, im_w - 1.0)
        by1 = mnp.clip(ncy - nh / 2, 0.0, im_h - 1.0)
        bx2 = mnp.clip(ncx + nw / 2, 0.0, im_w - 1.0)
        by2 = mnp.clip(ncy + nh / 2, 0.0, im_h - 1.0)
        return mnp.stack([bx1, by1, bx2, by2], axis=-1)  # (R, classes, 4)

    def forward(self, x):
        from ... import _tape, npx
        from ... import np as mnp
        B, _, H, W = x.shape
        feat = self.features(x)
        rpn_raw, rpn_prob, rpn_reg = self.rpn(feat)
        im_info = mnp.array(
            _np.tile(_np.asarray([[H, W, 1.0]], 'float32'), (B, 1)))
        rois = _op('proposal', rpn_prob, rpn_reg, im_info,
                   rpn_post_nms_top_n=self._post_nms,
                   scales=self._scales, ratios=self._ratios,
                   feature_stride=16)             # (B, R, 5)
        flat_rois = rois.reshape(-1, 5)
        pooled = _op('roi_align', feat, flat_rois,
                     (self._roi_size, self._roi_size), 1.0 / 16)
        h = self.head(pooled)
        cls_scores = self.cls_pred(h)             # (B*R, C+1)
        deltas = self.box_pred(h)                 # (B*R, 4C)

        # is_training (not is_recording): inside a hybridized trace the
        # recorder is off but the train flag carries through, so the
        # training branch compiles correctly under hybridize too
        if _tape.is_training():
            return rpn_raw, rpn_reg, cls_scores, deltas, rois

        probs = npx.softmax(cls_scores, axis=-1)[:, 1:]   # drop background
        boxes = self._decode_boxes(flat_rois, deltas, H, W)  # (B*R, C, 4)
        R = self._post_nms
        C = self._classes
        cls_ids = mnp.broadcast_to(
            mnp.arange(C).reshape(1, C), (B * R, C)).astype(x.dtype)
        dets = _op('concatenate',
                   [mnp.expand_dims(cls_ids, -1),
                    mnp.expand_dims(probs, -1), boxes], axis=-1)
        dets = dets.reshape(B, R * C, 6)
        return nms_detection_output(dets, self._nms_thresh, self._nms_topk,
                                    pre_nms=self._pre_nms)


def faster_rcnn_resnet50_v1(classes=20, **kwargs):
    """GluonCV-parity constructor name."""
    return FasterRCNN(classes=classes, **kwargs)
