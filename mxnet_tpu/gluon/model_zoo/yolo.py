"""YOLOv3 with a Darknet-53 backbone.

Reference: the BASELINE.json "GluonCV: YOLOv3" config (the reference repo
itself carries only the detection *operators* — multibox/box_nms families,
src/operator/contrib/ — GluonCV supplied the model). Re-designed TPU-first
rather than ported: every stage is static-shape, the three detection heads
decode with vectorized grid/anchor math (no per-cell Python), and NMS is
the framework's `npx.box_nms` (a sort + IoU-matrix kernel, fixed topk so
the output shape stays static under jit).

Layout is NCHW to match the rest of the zoo (XLA re-lays-out for TPU).
"""

import numpy as _np

from .. import nn
from ..block import HybridBlock
from ...ops.registry import get_op, invoke

__all__ = ['Darknet53', 'YOLOv3', 'darknet53', 'yolo3_darknet53']


def _op(name, *args, **kw):
    return invoke(get_op(name), args, kw)


def nms_detection_output(dets, nms_thresh, nms_topk, pre_nms=400):
    """Shared detector tail: (B, N, [id, score, x1, y1, x2, y2]) →
    per-class NMS → ``(ids, scores, boxes)``. Used by YOLOv3 and
    Faster R-CNN.

    The suppression step is quadratic in candidate count (box_nms builds
    an IoU matrix), so the N raw candidates are first cut to the
    ``pre_nms`` best by score — one lax.top_k — keeping the whole tail
    static-shape and HBM-sized (10k+ raw anchors would need a ~60 GB
    matrix otherwise)."""
    from ... import np as mnp
    n = dets.shape[1]
    if pre_nms and n > pre_nms:
        scores = dets[:, :, 1]
        idx = _op('topk', scores, axis=1, k=pre_nms, ret_typ='indices',
                  is_ascend=False, dtype='int32')
        dets = _op('take_along_axis', dets,
                   mnp.expand_dims(idx, -1).astype('int32'), 1)
    dets = _op('box_nms', dets, overlap_thresh=nms_thresh,
               valid_thresh=0.01, topk=nms_topk,
               coord_start=2, score_index=1, id_index=0)
    return (dets[:, :, 0], dets[:, :, 1], dets[:, :, 2:6])


def _conv_bn_leaky(channels, kernel, stride=1, padding=0):
    """Darknet conv unit: conv → BN → LeakyReLU(0.1)."""
    cell = nn.HybridSequential()
    cell.add(nn.Conv2D(channels, kernel_size=kernel, strides=stride,
                       padding=padding, use_bias=False))
    cell.add(nn.BatchNorm(epsilon=1e-5, momentum=0.9))
    cell.add(nn.LeakyReLU(0.1))
    return cell


class DarknetBasicBlock(HybridBlock):
    """Residual 1x1 → 3x3 block (Darknet-53 unit)."""

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv_bn_leaky(channels // 2, 1))
        self.body.add(_conv_bn_leaky(channels, 3, padding=1))

    def forward(self, x):
        return x + self.body(x)


class Darknet53(HybridBlock):
    """Darknet-53 backbone returning the three YOLO feature stages
    (strides 8, 16, 32)."""

    LAYERS = (1, 2, 8, 8, 4)
    CHANNELS = (64, 128, 256, 512, 1024)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.first = _conv_bn_leaky(32, 3, padding=1)
        self.stages = nn.HybridSequential()
        for n_layer, ch in zip(self.LAYERS, self.CHANNELS):
            stage = nn.HybridSequential()
            stage.add(_conv_bn_leaky(ch, 3, stride=2, padding=1))
            for _ in range(n_layer):
                stage.add(DarknetBasicBlock(ch))
            self.stages.add(stage)

    def forward(self, x):
        x = self.first(x)
        feats = []
        for i, stage in enumerate(self.stages._children.values()):
            x = stage(x)
            if i >= 2:            # strides 8, 16, 32
                feats.append(x)
        return tuple(feats)


class _YOLODetectionBlock(HybridBlock):
    """5-conv transition + the 3x3 lead-in to the output conv."""

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        for i in range(2):
            self.body.add(_conv_bn_leaky(channels, 1))
            self.body.add(_conv_bn_leaky(channels * 2, 3, padding=1))
        self.body.add(_conv_bn_leaky(channels, 1))
        self.tip = _conv_bn_leaky(channels * 2, 3, padding=1)

    def forward(self, x):
        route = self.body(x)
        return route, self.tip(route)


# COCO anchors (pixels, on a 416 canvas), 3 per output stage
_DEFAULT_ANCHORS = (
    ((116, 90), (156, 198), (373, 326)),    # stride 32
    ((30, 61), (62, 45), (59, 119)),        # stride 16
    ((10, 13), (16, 30), (33, 23)),         # stride 8
)
_STRIDES = (32, 16, 8)


class YOLOv3(HybridBlock):
    """Three-scale YOLOv3 head over Darknet-53.

    ``forward(x)`` returns raw per-stage predictions when training
    (autograd recording) and decoded ``(ids, scores, boxes)`` at
    inference: the whole decode — sigmoid offsets, grid add, anchor
    scale, NMS — is one static-shape compiled graph.
    """

    def __init__(self, classes=80, anchors=_DEFAULT_ANCHORS,
                 nms_thresh=0.45, nms_topk=100, **kwargs):
        super().__init__(**kwargs)
        self._classes = classes
        self._anchors = anchors
        self._nms_thresh = nms_thresh
        self._nms_topk = nms_topk
        self.backbone = Darknet53()
        self.blocks = nn.HybridSequential()
        self.outputs = nn.HybridSequential()
        self.routes = nn.HybridSequential()
        n_pred = 5 + classes
        for i, ch in enumerate((512, 256, 128)):
            self.blocks.add(_YOLODetectionBlock(ch))
            self.outputs.add(nn.Conv2D(len(anchors[i]) * n_pred,
                                       kernel_size=1))
            if i < 2:
                self.routes.add(_conv_bn_leaky(ch // 2, 1))

    def _decode_stage(self, pred, stage_idx):
        """(B, A*(5+C), H, W) → (B, H*W*A, 1+C+4) with boxes in input
        pixels. Anchors are in input-pixel units (GluonCV convention) —
        no canvas rescale, so rectangular inputs decode consistently."""
        from ... import np as mnp
        anchors = self._anchors[stage_idx]
        stride = _STRIDES[stage_idx]
        n_a = len(anchors)
        n_pred = 5 + self._classes
        B, _, H, W = pred.shape
        p = pred.reshape(B, n_a, n_pred, H, W)
        p = p.transpose(0, 3, 4, 1, 2)                # (B, H, W, A, 5+C)

        xy = _op('sigmoid', p[..., 0:2])
        wh = p[..., 2:4]
        obj = _op('sigmoid', p[..., 4:5])
        cls = _op('sigmoid', p[..., 5:])

        gy = mnp.arange(H).reshape(1, H, 1, 1, 1).astype(pred.dtype)
        gx = mnp.arange(W).reshape(1, 1, W, 1, 1).astype(pred.dtype)
        cx = (xy[..., 0:1] + gx) * stride
        cy = (xy[..., 1:2] + gy) * stride
        aw = mnp.array(_np.asarray([a[0] for a in anchors], 'float32')
                       ).reshape(1, 1, 1, n_a, 1).astype(pred.dtype)
        ah = mnp.array(_np.asarray([a[1] for a in anchors], 'float32')
                       ).reshape(1, 1, 1, n_a, 1).astype(pred.dtype)
        # clamp the log-size before exp: keeps garbage weights (or early
        # training) from emitting inf-sized boxes into NMS
        bw = _op('exp', _op('clip', wh[..., 0:1], -10.0, 8.0)) * aw
        bh = _op('exp', _op('clip', wh[..., 1:2], -10.0, 8.0)) * ah

        im_h, im_w = H * stride, W * stride
        x1 = _op('clip', cx - bw / 2, 0.0, im_w - 1.0)
        y1 = _op('clip', cy - bh / 2, 0.0, im_h - 1.0)
        x2 = _op('clip', cx + bw / 2, 0.0, im_w - 1.0)
        y2 = _op('clip', cy + bh / 2, 0.0, im_h - 1.0)
        out = _op('concatenate', [obj, cls, x1, y1, x2, y2], axis=-1)
        return out.reshape(B, H * W * n_a, 1 + self._classes + 4)

    def forward(self, x):
        from ... import _tape
        from ... import np as mnp
        feats = self.backbone(x)                      # strides 8, 16, 32
        c3, c4, c5 = feats

        stage_preds = []
        route = None
        for i, feat in enumerate((c5, c4, c3)):
            if route is not None:
                up = _op('upsampling', route, scale=2,
                         sample_type='nearest')
                feat = _op('concatenate', [up, feat], axis=1)
            route_in, tip = self.blocks[i](feat)
            stage_preds.append(self.outputs[i](tip))
            if i < 2:
                route = self.routes[i](route_in)

        # is_training (not is_recording): inside a hybridized trace the
        # recorder is off but the train flag carries through, so the
        # training branch compiles correctly under hybridize too
        if _tape.is_training():
            return tuple(stage_preds)                 # training: raw heads

        decoded = [self._decode_stage(p, i)
                   for i, p in enumerate(stage_preds)]
        all_pred = _op('concatenate', decoded, axis=1)  # (B, N, 1+C+4)
        obj = all_pred[:, :, 0:1]
        cls = all_pred[:, :, 1:1 + self._classes]
        boxes = all_pred[:, :, 1 + self._classes:]
        scores = obj * cls                             # (B, N, C)
        ids = mnp.expand_dims(scores.argmax(axis=-1), -1).astype(x.dtype)
        best = mnp.max(scores, axis=-1, keepdims=True)
        dets = _op('concatenate', [ids, best, boxes], axis=-1)
        return nms_detection_output(dets, self._nms_thresh, self._nms_topk)


def darknet53(**kwargs):
    return Darknet53(**kwargs)


def yolo3_darknet53(classes=80, **kwargs):
    """GluonCV-parity constructor name."""
    return YOLOv3(classes=classes, **kwargs)
