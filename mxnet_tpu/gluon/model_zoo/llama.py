"""Llama model family (decoder-only causal LM).

NEW capability over the reference (its model zoo is vision-only,
python/mxnet/gluon/model_zoo/vision/, and its longest-sequence asset is the
single-device fused attention ops, src/operator/contrib/transformer.cc:650).
This is the long-context flagship of the TPU build:

* pre-norm blocks with RMSNorm (``npx.rms_norm``), rotary position
  embeddings, grouped-query attention and SwiGLU MLP — the Llama-2/3
  architecture family;
* attention runs through the Pallas flash kernel
  (ops/pallas/flash_attention.py) — causal, no materialized score matrix;
* ``llama_partition_rules()`` gives Megatron-style PartitionSpecs for
  ``mx.parallel.shard_params`` so the same Block trains tensor-parallel
  over a mesh 'tp' axis, and sequence-parallel via
  ``mx.parallel.ring_attention`` at the SPMD layer;
* everything is a HybridBlock: one ``hybridize()`` compiles the whole
  decoder into a single XLA executable.
"""

import math

from jax.sharding import PartitionSpec as P

from ..block import HybridBlock
from ..parameter import Parameter
from .. import nn
from ... import initializer

__all__ = ['LlamaConfig', 'LlamaModel', 'LlamaForCausalLM', 'llama_tiny',
           'llama2_7b', 'llama3_8b', 'get_llama', 'llama_partition_rules']


class LlamaConfig:
    """Architecture hyperparameters. ``rope_theta`` is 1e4 for Llama-2
    lineage, 5e5 for Llama-3 (long-context)."""

    def __init__(self, vocab_size=32000, units=4096, num_layers=32,
                 num_heads=32, num_kv_heads=None, hidden_size=11008,
                 max_length=4096, rope_theta=10000.0, rms_norm_eps=1e-5,
                 tie_word_embeddings=False):
        self.vocab_size = vocab_size
        self.units = units
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        self.hidden_size = hidden_size
        self.max_length = max_length
        self.rope_theta = rope_theta
        self.rms_norm_eps = rms_norm_eps
        self.tie_word_embeddings = tie_word_embeddings
        assert units % num_heads == 0
        assert self.num_heads % self.num_kv_heads == 0


class RMSNorm(HybridBlock):
    """Root-mean-square norm (no mean subtraction, no bias)."""

    def __init__(self, units, epsilon=1e-5):
        super().__init__()
        self._eps = epsilon
        self.weight = Parameter('weight', shape=(units,),
                                init=initializer.One())

    def forward(self, x):
        from ... import npx
        return npx.rms_norm(x, self.weight.data(), eps=self._eps)


def _rope(x, theta, offset=0):
    """Apply rotary position embeddings to (B, S, H, Dh) — interleaved
    even/odd-pair convention (NOT HuggingFace's rotate-half: converting HF
    checkpoints requires their q/k weight permutation). Pure function of
    shape: folds into the jit as constants."""
    import jax.numpy as jnp
    _, S, _, Dh = x.shape
    inv = 1.0 / (theta ** (jnp.arange(0, Dh, 2, dtype=jnp.float32) / Dh))
    pos = jnp.arange(offset, offset + S, dtype=jnp.float32)
    ang = pos[:, None] * inv[None, :]                  # (S, Dh/2)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., ::2], x[..., 1::2]
    xf1 = x1.astype(jnp.float32)
    xf2 = x2.astype(jnp.float32)
    r1 = xf1 * cos - xf2 * sin
    r2 = xf2 * cos + xf1 * sin
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


class LlamaAttention(HybridBlock):
    """Grouped-query attention with RoPE; causal flash kernel.

    num_kv_heads < num_heads shares each K/V head across a group of Q
    heads (the Llama-2-70B / Llama-3 memory-bandwidth optimization); KV
    heads are broadcast to the full head count right before the kernel —
    XLA keeps the broadcast virtual."""

    def __init__(self, cfg):
        super().__init__()
        self._h = cfg.num_heads
        self._kv = cfg.num_kv_heads
        self._dh = cfg.units // cfg.num_heads
        self._theta = cfg.rope_theta
        self.q_proj = nn.Dense(self._h * self._dh, use_bias=False,
                               flatten=False)
        self.k_proj = nn.Dense(self._kv * self._dh, use_bias=False,
                               flatten=False)
        self.v_proj = nn.Dense(self._kv * self._dh, use_bias=False,
                               flatten=False)
        self.o_proj = nn.Dense(cfg.units, use_bias=False, flatten=False)

    def forward(self, x):
        import jax.numpy as jnp
        from ...ndarray.ndarray import NDArray
        from ...ops.pallas.flash_attention import flash_attention

        B, S, _ = x.shape
        q = self.q_proj(x)._data.reshape(B, S, self._h, self._dh)
        k = self.k_proj(x)._data.reshape(B, S, self._kv, self._dh)
        v = self.v_proj(x)._data.reshape(B, S, self._kv, self._dh)
        q = _rope(q, self._theta)
        k = _rope(k, self._theta)
        if self._kv != self._h:
            rep = self._h // self._kv
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        out = flash_attention(q.transpose(0, 2, 1, 3),
                              k.transpose(0, 2, 1, 3),
                              v.transpose(0, 2, 1, 3), causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(B, S, self._h * self._dh)
        return self.o_proj(NDArray(out))


class LlamaMLP(HybridBlock):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, cfg):
        super().__init__()
        self.gate_proj = nn.Dense(cfg.hidden_size, use_bias=False,
                                  flatten=False)
        self.up_proj = nn.Dense(cfg.hidden_size, use_bias=False,
                                flatten=False)
        self.down_proj = nn.Dense(cfg.units, use_bias=False, flatten=False)

    def forward(self, x):
        from ... import npx
        return self.down_proj(npx.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaBlock(HybridBlock):
    """Pre-norm decoder block."""

    def __init__(self, cfg):
        super().__init__()
        self.input_layernorm = RMSNorm(cfg.units, cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = RMSNorm(cfg.units, cfg.rms_norm_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x):
        x = x + self.self_attn(self.input_layernorm(x))
        return x + self.mlp(self.post_attention_layernorm(x))


class LlamaModel(HybridBlock):
    """Token embedding + decoder stack + final norm → hidden states."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.units)
        self.layers = []
        for i in range(cfg.num_layers):
            blk = LlamaBlock(cfg)
            self.register_child(blk, f'layers{i}')
            self.layers.append(blk)
        self.norm = RMSNorm(cfg.units, cfg.rms_norm_eps)

    def forward(self, token_ids):
        x = self.embed_tokens(token_ids)
        for blk in self.layers:
            x = blk(x)
        return self.norm(x)


class LlamaForCausalLM(HybridBlock):
    """Decoder LM head: (B, S) int tokens → (B, S, vocab) logits."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False,
                                    flatten=False)

    def forward(self, token_ids):
        from ... import np as mnp
        h = self.model(token_ids)
        if self.cfg.tie_word_embeddings:
            emb = self.model.embed_tokens.weight.data()
            return mnp.matmul(h, emb.T)
        return self.lm_head(h)


def llama_partition_rules(axis='tp'):
    """(predicate, PartitionSpec) rules for ``mx.parallel.shard_params``:
    Megatron layout — q/k/v/gate/up sharded on the output (head) dim,
    o/down on the input dim, embeddings on the vocab dim, norms replicated.
    gluon Dense stores weight as (units_out, units_in), so the output dim
    is axis 0."""
    def col(name, shape):   # output-dim (column-parallel) kernels
        return any(t in name for t in
                   ('q_proj', 'k_proj', 'v_proj', 'gate_proj', 'up_proj'))

    def row(name, shape):   # input-dim (row-parallel) kernels
        return any(t in name for t in ('o_proj', 'down_proj'))

    def embed(name, shape):
        return 'embed_tokens' in name or 'lm_head' in name

    return [
        (col, P(axis, None)),
        (row, P(None, axis)),
        (embed, P(axis, None)),
    ]


_LLAMA_CONFIGS = {
    # test-scale config (CI, unit tests)
    'llama_tiny': dict(vocab_size=256, units=64, num_layers=2, num_heads=4,
                       num_kv_heads=2, hidden_size=128, max_length=128,
                       rope_theta=10000.0),
    'llama2_7b': dict(vocab_size=32000, units=4096, num_layers=32,
                      num_heads=32, num_kv_heads=32, hidden_size=11008,
                      max_length=4096, rope_theta=10000.0),
    'llama3_8b': dict(vocab_size=128256, units=4096, num_layers=32,
                      num_heads=32, num_kv_heads=8, hidden_size=14336,
                      max_length=8192, rope_theta=500000.0),
}


def get_llama(name, **kwargs):
    cfg = dict(_LLAMA_CONFIGS[name])
    cfg.update(kwargs)
    return LlamaForCausalLM(LlamaConfig(**cfg))


def llama_tiny(**kwargs):
    """2-layer test-scale Llama (unit tests / smoke runs)."""
    return get_llama('llama_tiny', **kwargs)


def llama2_7b(**kwargs):
    """Llama-2-7B shapes."""
    return get_llama('llama2_7b', **kwargs)


def llama3_8b(**kwargs):
    """Llama-3-8B shapes (GQA 32/8, 500k rope theta)."""
    return get_llama('llama3_8b', **kwargs)
