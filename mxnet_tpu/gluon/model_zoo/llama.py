"""Llama model family (decoder-only causal LM).

NEW capability over the reference (its model zoo is vision-only,
python/mxnet/gluon/model_zoo/vision/, and its longest-sequence asset is the
single-device fused attention ops, src/operator/contrib/transformer.cc:650).
This is the long-context flagship of the TPU build:

* pre-norm blocks with RMSNorm (``npx.rms_norm``), rotary position
  embeddings, grouped-query attention and SwiGLU MLP — the Llama-2/3
  architecture family;
* attention runs through the Pallas flash kernel
  (ops/pallas/flash_attention.py) — causal, no materialized score matrix;
* ``llama_partition_rules()`` gives Megatron-style PartitionSpecs for
  ``mx.parallel.shard_params`` so the same Block trains tensor-parallel
  over a mesh 'tp' axis, and sequence-parallel via
  ``mx.parallel.ring_attention`` at the SPMD layer;
* everything is a HybridBlock: one ``hybridize()`` compiles the whole
  decoder into a single XLA executable.
"""

import math
from functools import partial

from jax.sharding import PartitionSpec as P

from ..block import HybridBlock
from ..parameter import Parameter
from .. import nn
from ... import initializer

__all__ = ['LlamaConfig', 'LlamaModel', 'LlamaForCausalLM', 'llama_tiny',
           'llama2_7b', 'llama3_8b', 'get_llama', 'llama_partition_rules']


class LlamaConfig:
    """Architecture hyperparameters. ``rope_theta`` is 1e4 for Llama-2
    lineage, 5e5 for Llama-3 (long-context)."""

    def __init__(self, vocab_size=32000, units=4096, num_layers=32,
                 num_heads=32, num_kv_heads=None, hidden_size=11008,
                 max_length=4096, rope_theta=10000.0, rms_norm_eps=1e-5,
                 tie_word_embeddings=False):
        self.vocab_size = vocab_size
        self.units = units
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        self.hidden_size = hidden_size
        self.max_length = max_length
        self.rope_theta = rope_theta
        self.rms_norm_eps = rms_norm_eps
        self.tie_word_embeddings = tie_word_embeddings
        assert units % num_heads == 0
        assert self.num_heads % self.num_kv_heads == 0


class RMSNorm(HybridBlock):
    """Root-mean-square norm (no mean subtraction, no bias)."""

    def __init__(self, units, epsilon=1e-5):
        super().__init__()
        self._eps = epsilon
        self.weight = Parameter('weight', shape=(units,),
                                init=initializer.One())

    def forward(self, x):
        from ... import npx
        return npx.rms_norm(x, self.weight.data(), eps=self._eps)


def _rope(x, theta, offset=0):
    """Apply rotary position embeddings to (B, S, H, Dh) — interleaved
    even/odd-pair convention (NOT HuggingFace's rotate-half: converting HF
    checkpoints requires their q/k weight permutation). Pure function of
    shape: folds into the jit as constants.

    ``offset`` is a scalar (shared position shift — prefill / lockstep
    decode) or a ``(B,)`` array of per-row positions (continuous-batching
    decode, where every cache slot sits at its own depth)."""
    import jax.numpy as jnp
    _, S, _, Dh = x.shape
    inv = 1.0 / (theta ** (jnp.arange(0, Dh, 2, dtype=jnp.float32) / Dh))
    # offset may be a traced scalar (jitted decode step): keep the arange
    # static and add the offset. atleast_1d makes the scalar and per-row
    # cases share one code path: pos is (1, S) or (B, S).
    pos = jnp.atleast_1d(jnp.asarray(offset, jnp.float32))[:, None] \
        + jnp.arange(S, dtype=jnp.float32)
    ang = pos[..., None] * inv[None, None, :]          # (1|B, S, Dh/2)
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., ::2], x[..., 1::2]
    xf1 = x1.astype(jnp.float32)
    xf2 = x2.astype(jnp.float32)
    r1 = xf1 * cos - xf2 * sin
    r2 = xf2 * cos + xf1 * sin
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


class LlamaAttention(HybridBlock):
    """Grouped-query attention with RoPE; causal flash kernel.

    num_kv_heads < num_heads shares each K/V head across a group of Q
    heads (the Llama-2-70B / Llama-3 memory-bandwidth optimization); KV
    heads are broadcast to the full head count right before the kernel —
    XLA keeps the broadcast virtual."""

    def __init__(self, cfg):
        super().__init__()
        self._h = cfg.num_heads
        self._kv = cfg.num_kv_heads
        self._dh = cfg.units // cfg.num_heads
        self._theta = cfg.rope_theta
        self.q_proj = nn.Dense(self._h * self._dh, use_bias=False,
                               flatten=False)
        self.k_proj = nn.Dense(self._kv * self._dh, use_bias=False,
                               flatten=False)
        self.v_proj = nn.Dense(self._kv * self._dh, use_bias=False,
                               flatten=False)
        self.o_proj = nn.Dense(cfg.units, use_bias=False, flatten=False)

    def forward(self, x, cache=None, offset=0, pages=None):
        """cache: optional (k_cache, v_cache) raw arrays of shape
        (B, max_len, kv_heads, dh) for incremental decode — new K/V are
        written at ``offset`` (static-shape ``dynamic_update_slice``, the
        TPU-idiomatic KV cache) and attention runs over the cache with an
        absolute-position causal mask. Returns out, or (out, new_cache).

        When ``offset`` is a ``(B,)`` array (continuous-batching decode,
        ``mx.serve.DecodeServer``) each batch row is an independent cache
        slot at its own depth: S must be 1, the new K/V land at
        ``offset[b]`` per row (vectorized scatter) and row b's query
        attends to cache positions ``<= offset[b]``.

        When ``pages`` is given (paged KV, vLLM-style), ``cache`` is the
        GLOBAL page pool ``(num_pages, page_size, kv_heads, dh)`` shared
        by every sequence and ``pages`` is the int32 block table
        ``(B, pages_per_seq)`` mapping row ``b``'s logical positions
        onto pool pages — a traced VALUE, so re-pointing a slot at
        different pages never retraces. Logical position ``p`` of row
        ``b`` lives at ``pool[pages[b, p // page_size], p % page_size]``.
        New K/V are scattered through the block table, then each row's
        logical cache is gathered back for attention; the causal mask is
        identical to the dense layout, so dead rows (block table full of
        the garbage page) compute garbage nobody reads. Supports the
        per-slot decode case (S == 1, ``offset`` is ``(B,)``) and the
        chunked-prefill case (B == 1, ``offset`` a scalar: queries at
        absolute positions ``offset + i``)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from ...ndarray.ndarray import NDArray
        from ...ops.pallas.flash_attention import flash_attention

        B, S, _ = x.shape
        q = self.q_proj(x)._data.reshape(B, S, self._h, self._dh)
        k = self.k_proj(x)._data.reshape(B, S, self._kv, self._dh)
        v = self.v_proj(x)._data.reshape(B, S, self._kv, self._dh)
        q = _rope(q, self._theta, offset=offset)
        k = _rope(k, self._theta, offset=offset)
        per_slot = getattr(offset, 'ndim', 0) == 1

        if cache is not None:
            k_cache, v_cache = cache
            if pages is not None:
                psz = k_cache.shape[1]
                L = pages.shape[1] * psz
                if per_slot:
                    assert S == 1, \
                        'per-slot offsets decode one token per step'
                    pid = pages[jnp.arange(B), offset // psz]      # (B,)
                    k_cache = k_cache.at[pid, offset % psz].set(
                        k[:, 0].astype(k_cache.dtype))
                    v_cache = v_cache.at[pid, offset % psz].set(
                        v[:, 0].astype(v_cache.dtype))
                    # decode reads the pool THROUGH the block table:
                    # Pallas kernel walks pages[b, i] on TPU, the
                    # original gather math runs off-TPU
                    # (ops/contrib.py: paged_attention_decode)
                    from ...ops.contrib import paged_attention_decode
                    out = paged_attention_decode(
                        q[:, 0], k_cache, v_cache, pages,
                        jnp.asarray(offset, jnp.int32),
                        sm_scale=self._dh ** -0.5)
                    out = out.reshape(B, S, self._h * self._dh)
                    return self.o_proj(NDArray(out)), (k_cache, v_cache)
                else:
                    assert B == 1, 'chunked prefill fills one sequence'
                    pos = jnp.asarray(offset, jnp.int32) + jnp.arange(S)
                    pid = pages[0, pos // psz]                     # (S,)
                    k_cache = k_cache.at[pid, pos % psz].set(
                        k[0].astype(k_cache.dtype))
                    v_cache = v_cache.at[pid, pos % psz].set(
                        v[0].astype(v_cache.dtype))
                # gather each row's logical cache out of the pool
                kf = k_cache[pages].reshape(B, L, self._kv, self._dh)
                vf = v_cache[pages].reshape(B, L, self._kv, self._dh)
            else:
                L = k_cache.shape[1]
                if per_slot:
                    assert S == 1, \
                        'per-slot offsets decode one token per step'
                    rows = jnp.arange(B)
                    k_cache = k_cache.at[rows, offset].set(
                        k[:, 0].astype(k_cache.dtype))
                    v_cache = v_cache.at[rows, offset].set(
                        v[:, 0].astype(v_cache.dtype))
                else:
                    k_cache = lax.dynamic_update_slice(
                        k_cache, k.astype(k_cache.dtype),
                        (0, offset, 0, 0))
                    v_cache = lax.dynamic_update_slice(
                        v_cache, v.astype(v_cache.dtype),
                        (0, offset, 0, 0))
                kf, vf = k_cache, v_cache
            rep = self._h // self._kv
            kf = jnp.repeat(kf, rep, 2) if rep > 1 else kf
            vf = jnp.repeat(vf, rep, 2) if rep > 1 else vf
            scores = jnp.einsum(
                'bshd,blhd->bhsl', q.astype(jnp.float32),
                kf.astype(jnp.float32)) * (self._dh ** -0.5)
            if per_slot:
                # row b's single query (absolute position offset[b]) sees
                # its own slots <= offset[b]
                mask = jnp.arange(L)[None, :] <= offset[:, None]  # (B, L)
                scores = jnp.where(mask[:, None, None, :], scores, -1e30)
            else:
                # query i (absolute position offset+i) sees slots <= it
                qpos = offset + jnp.arange(S)[:, None]
                mask = jnp.arange(L)[None, :] <= qpos        # (S, L)
                scores = jnp.where(mask[None, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum('bhsl,blhd->bshd', probs,
                             vf.astype(jnp.float32)).astype(x.dtype)
            out = out.reshape(B, S, self._h * self._dh)
            return self.o_proj(NDArray(out)), (k_cache, v_cache)

        if self._kv != self._h:
            rep = self._h // self._kv
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        out = flash_attention(q.transpose(0, 2, 1, 3),
                              k.transpose(0, 2, 1, 3),
                              v.transpose(0, 2, 1, 3), causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(B, S, self._h * self._dh)
        return self.o_proj(NDArray(out))


class LlamaMLP(HybridBlock):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, cfg):
        super().__init__()
        self.gate_proj = nn.Dense(cfg.hidden_size, use_bias=False,
                                  flatten=False)
        self.up_proj = nn.Dense(cfg.hidden_size, use_bias=False,
                                flatten=False)
        self.down_proj = nn.Dense(cfg.units, use_bias=False, flatten=False)

    def forward(self, x):
        from ... import npx
        return self.down_proj(npx.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaBlock(HybridBlock):
    """Pre-norm decoder block."""

    def __init__(self, cfg):
        super().__init__()
        self.input_layernorm = RMSNorm(cfg.units, cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = RMSNorm(cfg.units, cfg.rms_norm_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x, cache=None, offset=0, pages=None):
        if cache is None:
            x = x + self.self_attn(self.input_layernorm(x))
            return x + self.mlp(self.post_attention_layernorm(x))
        att, cache = self.self_attn(self.input_layernorm(x), cache=cache,
                                    offset=offset, pages=pages)
        x = x + att
        return x + self.mlp(self.post_attention_layernorm(x)), cache


class LlamaModel(HybridBlock):
    """Token embedding + decoder stack + final norm → hidden states."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.units)
        self.layers = []
        for i in range(cfg.num_layers):
            blk = LlamaBlock(cfg)
            self.register_child(blk, f'layers{i}')
            self.layers.append(blk)
        self.norm = RMSNorm(cfg.units, cfg.rms_norm_eps)

    def forward(self, token_ids, caches=None, offset=0, pages=None):
        x = self.embed_tokens(token_ids)
        if caches is None:
            for blk in self.layers:
                x = blk(x)
            return self.norm(x)
        new_caches = []
        for blk, cache in zip(self.layers, caches):
            x, cache = blk(x, cache=cache, offset=offset, pages=pages)
            new_caches.append(cache)
        return self.norm(x), new_caches


class LlamaForCausalLM(HybridBlock):
    """Decoder LM head: (B, S) int tokens → (B, S, vocab) logits."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False,
                                    flatten=False)

    def forward(self, token_ids, caches=None, offset=0, pages=None):
        from ... import np as mnp
        if caches is None:
            h = self.model(token_ids)
        else:
            h, caches = self.model(token_ids, caches=caches, offset=offset,
                                   pages=pages)
        if self.cfg.tie_word_embeddings:
            emb = self.model.embed_tokens.weight.data()
            logits = mnp.matmul(h, emb.T)
        else:
            logits = self.lm_head(h)
        return logits if caches is None else (logits, caches)

    def init_caches(self, batch_size, max_length=None, dtype='float32'):
        """Allocate per-layer KV caches: list of (k, v), each
        (B, max_length, kv_heads, dh).

        ``batch_size`` is a free parameter, not hard-wired to one value:
        re-initializing at a different *bucketed* batch size reuses the
        per-step compiled fn as long as the bucket matches — callers with
        varying live batch sizes pad rows up to a bucket (see
        ``generate(batch_bucket=...)``) or hand slots out of a fixed-size
        pool (``mx.serve.DecodeServer``), masking/ignoring retired rows
        instead of retracing."""
        import jax.numpy as jnp
        cfg = self.cfg
        L = max_length or cfg.max_length
        dh = cfg.units // cfg.num_heads
        shape = (batch_size, L, cfg.num_kv_heads, dh)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in range(cfg.num_layers)]

    def init_paged_pool(self, num_pages, page_size, dtype='float32'):
        """Allocate the paged-KV pool: per layer, (k, v) arrays of shape
        ``(num_pages, page_size, kv_heads, dh)``. Unlike
        :meth:`init_caches` there is no batch dimension — every
        sequence's cache is a set of pages it names through its block
        table (``forward(..., pages=...)``), so pool bytes are a memory
        budget decoupled from both the decode batch shape and any
        per-sequence ``max_length`` reservation."""
        import jax.numpy as jnp
        cfg = self.cfg
        dh = cfg.units // cfg.num_heads
        shape = (num_pages, page_size, cfg.num_kv_heads, dh)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                for _ in range(cfg.num_layers)]

    def _param_run(self):
        """The decode-step closure shared by :meth:`generate` and
        ``mx.serve.DecodeServer``: a pure ``run(praws, tok_raw, caches,
        offset) -> (logits_raw, caches)`` over raw parameter arrays
        (traceable — swaps the raws into the Parameters for the span of
        one forward), plus the current praws mapping."""
        from ... import _tape
        from ...ndarray.ndarray import NDArray

        params = self.collect_params()
        praws = {name: p.data()._data for name, p in params.items()}

        def run(praws_, tok, caches, offset, pages=None):
            saved = []
            prev = _tape.set_recording(False)
            try:
                for name, p in params.items():
                    saved.append((p, p._data))
                    p._data = {c: NDArray(praws_[name]) for c in p._data}
                logits, caches = self.forward(NDArray(tok), caches=caches,
                                              offset=offset, pages=pages)
                return logits._data, caches
            finally:
                for p, d in saved:
                    p._data = d
                _tape.set_recording(prev)

        return run, praws

    def generate(self, token_ids, max_new_tokens=32, max_length=None,
                 temperature=0.0, seed=0, batch_bucket=None):
        """Autoregressive generation with a static-shape KV cache.

        TPU design: prefill is one jitted call over the whole prompt; each
        decode step is ONE jitted call reused for every position (the
        offset enters as a traced scalar, so there is exactly one compile
        for the prefill shape and one for the (B, 1) decode shape — no
        per-position retracing). Greedy when ``temperature == 0``, else
        temperature sampling.

        token_ids: (B, S) NDArray / array of prompt tokens.
        Returns (B, S + max_new_tokens) NDArray.

        ``batch_bucket`` pads the batch dim up to a declared bucket size
        (dummy rows, sliced off the result) so varying live batch sizes
        share ONE set of compiled prefill/decode programs and one cache
        shape — re-running at a different B within the bucket neither
        re-traces the per-step fn nor reallocates a differently-shaped
        cache. Batch rows are independent under causal attention, so the
        dummy rows cannot perturb the real ones.
        """
        import jax
        import jax.numpy as jnp
        from ...ndarray.ndarray import NDArray

        toks = token_ids._data if isinstance(token_ids, NDArray) \
            else jnp.asarray(token_ids)
        toks = toks.astype(jnp.int32)
        B_req, S = toks.shape
        if batch_bucket is not None:
            if batch_bucket < B_req:
                raise ValueError(
                    f'batch_bucket={batch_bucket} smaller than the '
                    f'actual batch {B_req}')
            if batch_bucket > B_req:
                toks = jnp.concatenate(
                    [toks, jnp.zeros((batch_bucket - B_req, S),
                                     jnp.int32)])
        B = toks.shape[0]
        # default cache length is sized from the power-of-two-rounded
        # decode budget (not the tight S + max_new_tokens), so
        # varying-length generate() calls land on a handful of compiled
        # (cache-shape, scan-length) programs instead of one per n
        n_pow2 = 1
        while n_pow2 < max(max_new_tokens - 1, 1):
            n_pow2 *= 2
        L = max_length or min(self.cfg.max_length, S + n_pow2 + 1)
        assert S + max_new_tokens <= L, 'max_length too small'

        run, praws = self._param_run()

        def pick(logits, key):
            last = logits[:, -1, :].astype(jnp.float32)
            if temperature <= 0.0:
                return jnp.argmax(last, axis=-1).astype(jnp.int32)
            return jax.random.categorical(
                key, last / temperature, axis=-1).astype(jnp.int32)

        # compiled steps are cached so repeat generate() calls skip
        # tracing; cache buffers are donated (≙ static_alloc's buffer
        # reuse). The whole decode loop is ONE lax.scan program: no
        # per-token host dispatch at all — the Python-loop equivalent
        # pays a dispatch round-trip per token, which at ~1 ms/token
        # decode speed is a measurable tax. The prefill key excludes
        # n_new (it doesn't depend on it); the scan length does enter
        # the decode key, so n_new is rounded up to a power of two and
        # excess tokens are computed-and-dropped — varying-length
        # generate() calls hit a handful of compiled programs instead of
        # one per distinct n.
        n_rest = max_new_tokens - 1
        n_pad = min(n_pow2, L - S - 1)
        psig = (B, S, L, float(temperature))
        dsig = psig + (n_pad,)
        steps = getattr(self, '_gen_steps', None)
        if steps is None:
            steps = self._gen_steps = {}
        if len(steps) > 16:    # bound compiled-executable growth
            steps.pop(next(iter(steps)))
        if psig in steps:
            prefill = steps[psig]
        else:
            @jax.jit
            def prefill(praws_, tok, caches, key):
                logits, caches = run(praws_, tok, caches, 0)
                return pick(logits, key), caches

            steps[psig] = prefill
        if dsig in steps:
            decode_n = steps[dsig]
        else:
            @partial(jax.jit, donate_argnums=(2,))
            def decode_n(praws_, tok, caches, offset, key):
                def body(carry, _):
                    nxt, ch, off, k = carry
                    k, sub = jax.random.split(k)
                    logits, ch = run(praws_, nxt[:, None], ch, off)
                    nxt = pick(logits, sub)
                    return (nxt, ch, off + 1, k), nxt

                (_, caches_, _, _), toks_out = jax.lax.scan(
                    body, (tok, caches, offset, key), None, length=n_pad)
                return toks_out, caches_    # (n_pad, B)

            steps[dsig] = decode_n

        key = jax.random.PRNGKey(seed)
        caches = self.init_caches(B, L)
        key, sub = jax.random.split(key)
        nxt, caches = prefill(praws, toks, caches, sub)
        out = [toks, nxt[:, None]]
        if max_new_tokens > 1:
            rest, caches = decode_n(praws, nxt, caches,
                                    jnp.asarray(S, jnp.int32), key)
            out.append(rest[:n_rest].T)   # drop pad-to-power-of-2 excess
        full = jnp.concatenate(out, axis=1)
        return NDArray(full[:B_req])      # drop batch-bucket dummy rows


def llama_partition_rules(axis='tp'):
    """(predicate, PartitionSpec) rules for ``mx.parallel.shard_params``:
    Megatron layout — q/k/v/gate/up sharded on the output (head) dim,
    o/down on the input dim, embeddings on the vocab dim, norms replicated.
    gluon Dense stores weight as (units_out, units_in), so the output dim
    is axis 0.

    Derived from the ``mx.sharding`` registry's ``('llama', 'tp')``
    table — one source of truth for every sharded surface — and exposed
    as legacy ``pred(name, shape)`` callables for existing
    ``shard_params`` callers. ``axis`` renames the mesh axis in the
    returned specs ('tp' in the registry)."""
    import re as _re
    from ...sharding import rules_for

    def _remap(spec):
        if axis == 'tp':
            return spec
        out = []
        for e in tuple(spec):
            if isinstance(e, tuple):
                out.append(tuple(axis if a == 'tp' else a for a in e))
            else:
                out.append(axis if e == 'tp' else e)
        return P(*out)

    rules = []
    for pattern, spec in rules_for('llama', 'tp'):
        if callable(pattern) and not isinstance(pattern, _re.Pattern):
            pred = pattern
        else:
            creg = _re.compile(pattern) if isinstance(pattern, str) \
                else pattern

            def pred(name, shape, _c=creg):
                return _c.search(name) is not None
            pred.__name__ = getattr(creg, 'pattern', str(pattern))
        rules.append((pred, _remap(spec)))
    return rules


_LLAMA_CONFIGS = {
    # test-scale config (CI, unit tests)
    'llama_tiny': dict(vocab_size=256, units=64, num_layers=2, num_heads=4,
                       num_kv_heads=2, hidden_size=128, max_length=128,
                       rope_theta=10000.0),
    'llama2_7b': dict(vocab_size=32000, units=4096, num_layers=32,
                      num_heads=32, num_kv_heads=32, hidden_size=11008,
                      max_length=4096, rope_theta=10000.0),
    'llama3_8b': dict(vocab_size=128256, units=4096, num_layers=32,
                      num_heads=32, num_kv_heads=8, hidden_size=14336,
                      max_length=8192, rope_theta=500000.0),
}


def get_llama(name, **kwargs):
    cfg = dict(_LLAMA_CONFIGS[name])
    cfg.update(kwargs)
    return LlamaForCausalLM(LlamaConfig(**cfg))


def llama_tiny(**kwargs):
    """2-layer test-scale Llama (unit tests / smoke runs)."""
    return get_llama('llama_tiny', **kwargs)


def llama2_7b(**kwargs):
    """Llama-2-7B shapes."""
    return get_llama('llama2_7b', **kwargs)


def llama3_8b(**kwargs):
    """Llama-3-8B shapes (GQA 32/8, 500k rope theta)."""
    return get_llama('llama3_8b', **kwargs)


def _hf_to_interleaved(w, num_heads, head_dim):
    """Permute q/k projection rows from HF rotate-half RoPE layout to the
    interleaved even/odd-pair layout `_rope` uses: per head, interleaved
    row 2j is HF row j, row 2j+1 is HF row j + head_dim/2 (both conventions
    then rotate pair j with the same frequency theta^(-2j/d))."""
    import numpy as np
    w = np.asarray(w)
    half = head_dim // 2
    perm = np.empty(head_dim, np.int64)
    perm[0::2] = np.arange(half)
    perm[1::2] = np.arange(half) + half
    w = w.reshape(num_heads, head_dim, -1)[:, perm]
    return w.reshape(num_heads * head_dim, -1)


def load_hf_state_dict(net, state_dict):
    """Load HuggingFace-Transformers Llama weights into an initialized
    :class:`LlamaForCausalLM` (the model-zoo pretrained-load surface, ≙
    model_store.py — local weights only, no downloads).

    ``state_dict``: mapping of HF parameter names to arrays (torch tensors
    or numpy). q/k projections are re-permuted for the interleaved RoPE
    convention (see ``_rope``); everything else maps 1:1.
    """
    import numpy as np

    cfg = net.cfg
    dh = cfg.units // cfg.num_heads

    def to_np(v):
        if hasattr(v, 'detach'):
            v = v.detach().cpu().float().numpy()
        return np.asarray(v, np.float32)

    params = net.collect_params()
    loaded = set()
    for hf_name, value in state_dict.items():
        name = hf_name
        # HF 'model.layers.0.' → gluon child name 'model.layers0.'
        while '.layers.' in name:
            head, rest = name.split('.layers.', 1)
            idx, rest = rest.split('.', 1)
            name = f'{head}.layers{idx}.{rest}'
        if name not in params:
            raise KeyError(f'{hf_name} has no target parameter ({name})')
        v = to_np(value)
        if name.endswith('self_attn.q_proj.weight'):
            v = _hf_to_interleaved(v, cfg.num_heads, dh)
        elif name.endswith('self_attn.k_proj.weight'):
            v = _hf_to_interleaved(v, cfg.num_kv_heads, dh)
        p = params[name]
        if tuple(p.shape) != v.shape:
            raise ValueError(
                f'{hf_name}: shape {v.shape} vs parameter {tuple(p.shape)}')
        p.set_data(v)
        loaded.add(name)
    missing = set(params) - loaded
    if missing:
        raise ValueError(f'checkpoint missing parameters: {sorted(missing)}')
    return net


def from_hf_pretrained(model_path, **config_overrides):
    """Build a LlamaForCausalLM from a local HuggingFace checkpoint
    directory (config.json + weights). Gated on the ``transformers``
    package; never downloads."""
    import json
    import os

    with open(os.path.join(model_path, 'config.json')) as f:
        hf_cfg = json.load(f)
    cfg = dict(
        vocab_size=hf_cfg['vocab_size'], units=hf_cfg['hidden_size'],
        num_layers=hf_cfg['num_hidden_layers'],
        num_heads=hf_cfg['num_attention_heads'],
        num_kv_heads=hf_cfg.get('num_key_value_heads',
                                hf_cfg['num_attention_heads']),
        hidden_size=hf_cfg['intermediate_size'],
        max_length=hf_cfg.get('max_position_embeddings', 4096),
        rope_theta=hf_cfg.get('rope_theta', 10000.0),
        rms_norm_eps=hf_cfg.get('rms_norm_eps', 1e-5),
        tie_word_embeddings=hf_cfg.get('tie_word_embeddings', False))
    cfg.update(config_overrides)
    net = LlamaForCausalLM(LlamaConfig(**cfg))
    net.initialize()
    import numpy as np
    B = 1
    net(__import__('mxnet_tpu').np.zeros((B, 2)))   # materialize params

    import transformers
    hf = transformers.AutoModelForCausalLM.from_pretrained(
        model_path, local_files_only=True)
    load_hf_state_dict(net, hf.state_dict())
    return net
