"""Local pretrained-weight store + universal checkpoint importer.

Reference: ``python/mxnet/gluon/model_zoo/model_store.py:31`` — a
sha1-pinned download zoo (``get_model_file`` fetches
``<name>-<sha1[:8]>.params`` from the MXNet S3 bucket). This
environment is zero-egress, so the store resolves LOCAL files instead,
and goes further than the reference: any of four checkpoint formats
imports into any zoo factory.

``get_model(name, pretrained=...)`` accepts:

* ``True`` — resolve ``$MXNET_HOME/models/<name>.<ext>`` (default root
  ``~/.mxnet/models``, same layout as the reference's cache dir) over
  the supported extensions;
* a path string — import that file directly.

Supported formats (sniffed by extension, then content):

* native params map (``Block.save_parameters`` / ``mx.nd.save`` npz);
* any raw numpy ``.npz`` archive;
* ``.safetensors`` (HuggingFace-style tensor map);
* torch ``.pt``/``.pth`` state_dict (torchvision weights) — loaded
  with ``weights_only=True`` so no pickled code executes.

Key matching, in order: exact structural names; suffix-normalized names
(dots/double-underscores unified, common framework prefixes stripped);
finally positional order with exact shape agreement — valid because an
architecturally identical checkpoint enumerates parameters in
construction order on both sides (torch state_dicts drop the
``num_batches_tracked`` bookkeeping on read so the counts line up; the
torch BN weight/bias at position k are gluon's gamma/beta at the same
position). A mismatch raises with a summary of what matched instead of
silently leaving random weights.
"""

import os as _os
import re as _re

import numpy as _onp

_EXTS = ('.params.npz', '.params', '.npz', '.safetensors', '.pt', '.pth')


def get_model_file(name, root=None):
    """Resolve a local weights file for ``name`` (reference
    model_store.get_model_file, minus the download)."""
    root = _os.path.expanduser(root or _os.path.join(
        _os.environ.get('MXNET_HOME', '~/.mxnet'), 'models'))
    for ext in _EXTS:
        path = _os.path.join(root, name + ext)
        if _os.path.exists(path):
            return path
    raise FileNotFoundError(
        f'no local pretrained weights for {name!r} under {root} '
        f'(tried {", ".join(_EXTS)}); place a checkpoint there or pass '
        f'pretrained=<path> (zero-egress: the reference would download '
        f'from the model store here)')


def read_checkpoint(path):
    """Load any supported checkpoint into {name: numpy array}."""
    low = str(path).lower()
    if low.endswith('.safetensors'):
        from safetensors.numpy import load_file
        return dict(load_file(path))
    if low.endswith(('.pt', '.pth')):
        import torch
        state = torch.load(path, map_location='cpu', weights_only=True)
        if hasattr(state, 'state_dict'):
            state = state.state_dict()
        out = {}
        for k, v in state.items():
            if k.endswith('num_batches_tracked'):
                # torch BatchNorm bookkeeping with no gluon counterpart;
                # keeping it would break the position+shape fallback
                continue
            if hasattr(v, 'detach'):
                t = v.detach().cpu()
                if t.dtype == torch.bfloat16:
                    t = t.float()       # numpy has no native bfloat16
                out[k] = t.numpy()
        return out
    # npz family (native map or raw archive)
    with _onp.load(path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files if not k.startswith('__mx')}


def _norm(name):
    """Normalize a parameter name to a comparable suffix form."""
    n = name.replace('__', '.').replace('_', '.')
    n = _re.sub(r'^(module|model|net|features|backbone)\.', '', n)
    return n


def match_params(targets, source, allow_missing=False):
    """Map checkpoint entries onto structural parameter names.

    ``targets``: {structural_name: Parameter}; ``source``:
    {name: ndarray}. Returns {structural_name: ndarray}.
    """
    out = {}
    # pass 1: exact names
    for name in targets:
        if name in source:
            out[name] = source[name]
    if len(out) == len(targets):
        return out
    # pass 2: normalized-suffix match (unique suffixes only)
    tnorm = {name: _norm(name) for name in targets if name not in out}
    snorm = {}
    for k in source:
        snorm.setdefault(_norm(k), []).append(k)
    for name, nn in tnorm.items():
        cands = snorm.get(nn, [])
        if len(cands) == 1:
            out[name] = source[cands[0]]
    if len(out) == len(targets):
        return out
    # pass 3: positional with exact shape agreement — valid when the
    # architectures are identical and only naming schemes differ
    remaining_t = [n for n in targets if n not in out]
    used = {id(v) for v in out.values()}
    remaining_s = [k for k in source if id(source[k]) not in used
                   and k not in out]
    if len(remaining_t) == len(remaining_s):
        pairs = []
        ok = True
        for tn, sn in zip(remaining_t, remaining_s):
            tshape = tuple(targets[tn].shape or ())
            known = tshape and all(d for d in tshape)
            if known and tuple(source[sn].shape) != tshape:
                ok = False
                break
            pairs.append((tn, sn))
        if ok:
            for tn, sn in pairs:
                out[tn] = source[sn]
            return out
    if allow_missing:
        return out
    missing = [n for n in targets if n not in out]
    raise ValueError(
        f'pretrained import matched {len(out)}/{len(targets)} '
        f'parameters; unmatched: {missing[:5]}{"..." if len(missing) > 5 else ""} '
        f'(checkpoint has {len(source)} entries). Pass a checkpoint '
        'for this architecture, or allow_missing=True.')


def apply_pretrained(net, pretrained, name, ctx=None, root=None):
    """Load pretrained weights into a freshly-built zoo net.

    ``pretrained``: True (resolve from the local store root) or a path.
    Called by every vision factory; a no-op when ``pretrained`` is
    falsy so factories can pass it straight through."""
    if not pretrained:
        return net
    from ...ndarray.ndarray import NDArray
    path = pretrained if isinstance(pretrained, (str, _os.PathLike)) \
        else get_model_file(name, root)
    source = read_checkpoint(path)
    if not net._initialized_once():
        net.initialize(ctx=ctx)
    params = net.collect_params()
    matched = match_params(params, source)
    for pname, arr in matched.items():
        p = params[pname]
        if isinstance(arr, NDArray):
            p.set_data(arr)
        else:
            a = _onp.asarray(arr)
            want = tuple(p.shape or ())
            # dims still 0 are deferred-unknown; set_data resolves them
            if want and all(d for d in want) and tuple(a.shape) != want:
                raise ValueError(
                    f'{pname}: checkpoint shape {a.shape} != parameter '
                    f'shape {want} ({path})')
            from ...ndarray.ndarray import array
            p.set_data(array(a))
    return net
