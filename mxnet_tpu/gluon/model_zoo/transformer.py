"""Transformer-base for machine translation (encoder–decoder).

Reference: the BASELINE.json "GluonNLP: Transformer-base MT" config — the
Vaswani et al. base arrangement (6+6 layers, 512 units, 8 heads, 2048 FFN,
sinusoidal positions, post-LN, tied target embedding/projection). The
reference repo only ships the fused attention operators
(src/operator/contrib/transformer.cc:650-826); the model itself lived in
GluonNLP. Built TPU-first: fused QKV projections (one MXU matmul), the
flash-attention path for causal/unmasked attention, static shapes, and a
greedy ``translate`` whose decode loop is compiled per step like the
Llama generator.
"""

import math

import numpy as _np

from .. import nn
from ..block import HybridBlock
from ..parameter import Parameter
from ... import initializer
from ...ops.registry import get_op, invoke

__all__ = ['TransformerMT', 'transformer_base_mt']


def _op(name, *args, **kw):
    return invoke(get_op(name), args, kw)


def _sinusoid_table(length, units):
    pos = _np.arange(length)[:, None]
    dim = _np.arange(units // 2)[None, :]
    angle = pos / _np.power(10000.0, 2 * dim / units)
    table = _np.zeros((length, units), 'float32')
    table[:, 0::2] = _np.sin(angle)
    table[:, 1::2] = _np.cos(angle)
    return table


class MultiHeadAttention(HybridBlock):
    """Self- or cross-attention; self mode fuses QKV into one matmul."""

    def __init__(self, units, num_heads, dropout=0.0, self_attn=True):
        super().__init__()
        self._num_heads = num_heads
        self._self = self_attn
        if self_attn:
            self.qkv = nn.Dense(3 * units, flatten=False)
        else:
            self.q_proj = nn.Dense(units, flatten=False)
            self.kv = nn.Dense(2 * units, flatten=False)
        self.proj = nn.Dense(units, flatten=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x, mem=None, mask=None, causal=False):
        from ... import npx
        if self._self:
            q, k, v = npx.split(self.qkv(x), 3, axis=-1)
        else:
            q = self.q_proj(x)
            k, v = npx.split(self.kv(mem), 2, axis=-1)
        out = npx.multi_head_attention(q, k, v, self._num_heads, mask=mask,
                                       causal=causal)
        out = self.proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class _FFN(HybridBlock):
    def __init__(self, units, hidden, dropout=0.0):
        super().__init__()
        self.fc1 = nn.Dense(hidden, flatten=False)
        self.fc2 = nn.Dense(units, flatten=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        h = self.fc2(_op('relu', self.fc1(x)))
        if self.dropout is not None:
            h = self.dropout(h)
        return h


class EncoderCell(HybridBlock):
    def __init__(self, units, hidden, num_heads, dropout=0.0):
        super().__init__()
        self.attn = MultiHeadAttention(units, num_heads, dropout)
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.ffn = _FFN(units, hidden, dropout)
        self.ln2 = nn.LayerNorm(in_channels=units)

    def forward(self, x, mask=None):
        x = self.ln1(x + self.attn(x, mask=mask))
        return self.ln2(x + self.ffn(x))


class DecoderCell(HybridBlock):
    def __init__(self, units, hidden, num_heads, dropout=0.0):
        super().__init__()
        self.self_attn = MultiHeadAttention(units, num_heads, dropout)
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.cross_attn = MultiHeadAttention(units, num_heads, dropout,
                                             self_attn=False)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.ffn = _FFN(units, hidden, dropout)
        self.ln3 = nn.LayerNorm(in_channels=units)

    def forward(self, x, mem, mem_mask=None):
        x = self.ln1(x + self.self_attn(x, causal=True))
        x = self.ln2(x + self.cross_attn(x, mem=mem, mask=mem_mask))
        return self.ln3(x + self.ffn(x))


class TransformerMT(HybridBlock):
    """Encoder–decoder translation model.

    ``forward(src, tgt)`` → (B, T_tgt, vocab_tgt) logits (teacher
    forcing). ``translate(src)`` → greedy-decoded target ids.
    """

    def __init__(self, src_vocab=32000, tgt_vocab=32000, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8, dropout=0.1,
                 max_length=512, tie_weights=True, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        self._tie = tie_weights
        self.src_embed = nn.Embedding(src_vocab, units)
        self.tgt_embed = nn.Embedding(tgt_vocab, units)
        self.pos_table = Parameter(
            'pos_table', shape=(max_length, units),
            init=initializer.Constant(_sinusoid_table(max_length, units)),
            differentiable=False)
        self.enc_layers = nn.HybridSequential()
        self.dec_layers = nn.HybridSequential()
        for _ in range(num_layers):
            self.enc_layers.add(EncoderCell(units, hidden_size, num_heads,
                                            dropout))
            self.dec_layers.add(DecoderCell(units, hidden_size, num_heads,
                                            dropout))
        self.dropout = nn.Dropout(dropout) if dropout else None
        if not tie_weights:
            self.out_proj = nn.Dense(tgt_vocab, flatten=False)

    def _embed(self, tokens, embed):
        from ... import np as mnp
        x = embed(tokens) * math.sqrt(self._units)
        pos = self.pos_table.data()[:tokens.shape[1]]
        x = x + mnp.expand_dims(pos, 0)
        if self.dropout is not None:
            x = self.dropout(x)
        return x

    @staticmethod
    def _src_mask(batch, t_k, valid_length, t_q):
        from ... import np as mnp
        if valid_length is None:
            return None
        pos = mnp.arange(t_k).reshape(1, t_k)
        valid = pos < mnp.expand_dims(valid_length, -1)     # (B, Tk)
        m = mnp.expand_dims(mnp.expand_dims(valid, 1), 1)   # (B,1,1,Tk)
        return mnp.broadcast_to(m, (batch, 1, t_q, t_k))

    def encode(self, src, valid_length=None):
        x = self._embed(src, self.src_embed)
        mask = self._src_mask(src.shape[0], src.shape[1], valid_length,
                              src.shape[1])
        for cell in self.enc_layers._children.values():
            x = cell(x, mask=mask)
        return x

    def decode(self, tgt, mem, valid_length=None):
        """mem: encoder output (B, T_src, units) — carries the source
        shape, so no src tokens are needed here."""
        x = self._embed(tgt, self.tgt_embed)
        mem_mask = self._src_mask(mem.shape[0], mem.shape[1], valid_length,
                                  tgt.shape[1])
        for cell in self.dec_layers._children.values():
            x = cell(x, mem, mem_mask=mem_mask)
        if self._tie:
            w = self.tgt_embed.weight.data()
            return _op('fully_connected', x.reshape(-1, self._units), w,
                       no_bias=True).reshape(
                           x.shape[0], x.shape[1], -1)
        return self.out_proj(x)

    def forward(self, src, tgt, valid_length=None):
        mem = self.encode(src, valid_length)
        return self.decode(tgt, mem, valid_length=valid_length)

    def translate(self, src, max_new_tokens=32, bos_id=2, eos_id=3,
                  valid_length=None):
        """Greedy decode with EOS handling: finished sequences keep
        emitting ``eos_id``, and the loop stops early once every
        sequence has finished. The per-step decoder run recomputes the
        causal prefix (teacher-forcing shape) — O(T^2) but one compiled
        graph per prefix length; a KV-cache decode like the Llama
        generator is the next optimization step."""
        import numpy as onp
        from ... import np as mnp
        mem = self.encode(src, valid_length)
        B = src.shape[0]
        tgt = mnp.full((B, 1), float(bos_id)).astype('int32')
        finished = onp.zeros((B,), bool)
        for _ in range(max_new_tokens):
            logits = self.decode(tgt, mem, valid_length=valid_length)
            nxt = logits[:, -1, :].argmax(-1).astype('int32')
            nxt_np = onp.array(nxt.asnumpy())   # asnumpy view is read-only
            nxt_np[finished] = eos_id
            finished |= (nxt_np == eos_id)
            tgt = _op('concatenate',
                      [tgt, mnp.array(nxt_np.reshape(B, 1))], axis=1)
            if finished.all():
                break
        return tgt


def transformer_base_mt(src_vocab=32000, tgt_vocab=32000, **kwargs):
    """Vaswani base configuration."""
    return TransformerMT(src_vocab=src_vocab, tgt_vocab=tgt_vocab, **kwargs)
