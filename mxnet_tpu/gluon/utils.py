"""``gluon.utils`` (reference python/mxnet/gluon/utils.py)."""

import numpy as _np

from ..ndarray.ndarray import NDArray, array


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Reference utils.py:split_data."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f'data with shape {data.shape} cannot be evenly split into '
            f'{num_slice} slices along axis {batch_axis}.')
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Reference utils.py:split_and_load — see also
    mxnet_tpu.parallel.split_and_load for the mesh-sharded form."""
    if not isinstance(data, NDArray):
        data = array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Reference utils.py:clip_global_norm."""
    import jax.numpy as jnp
    assert len(arrays) > 0
    total = jnp.sqrt(sum(jnp.sum(a._data.astype(jnp.float32) ** 2)
                         for a in arrays))
    total_norm = float(total)
    if check_isfinite and not _np.isfinite(total_norm):
        import warnings
        warnings.warn('nan or inf is detected. Clipping results will be '
                      'undefined.', stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr._rebind(arr._data * scale)
    return total_norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, 'rb') as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Reference utils.py:download. No egress in CI — raises with a clear
    message when the network is unavailable."""
    import os
    import urllib.request
    fname = path or url.split('/')[-1]
    if os.path.isdir(fname):
        fname = os.path.join(fname, url.split('/')[-1])
    if not overwrite and os.path.exists(fname) and (
            not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    try:
        urllib.request.urlretrieve(url, fname)
    except Exception as e:
        raise OSError(
            f'Failed to download {url} (offline environment?). Place the '
            f'file at {fname} manually.') from e
    return fname


def shape_is_known(shape):
    if shape is None:
        return False
    return all(s is not None and s > 0 for s in shape)


def _indent(s, num_spaces):
    lines = s.split('\n')
    first = lines.pop(0)
    return first + '\n'.join(' ' * num_spaces + line for line in lines)
