"""Contrib text datasets (reference
``python/mxnet/gluon/contrib/data/text.py`` — WikiText language-model
datasets).

Zero-egress build: datasets load from local files only (pass ``root``
pointing at pre-downloaded ``wiki.{train,validation,test}.tokens``);
the reference's download path raises a clear error here instead of
fetching. Tokenization/vocabulary come from ``contrib.text``.
"""

import os

import numpy as onp

from ...data.dataset import SimpleDataset
from ....contrib import text as _text

__all__ = ['WikiText2', 'WikiText103']


class _LanguageModelDataset(SimpleDataset):
    """Token-id sequence dataset cut into `seq_len` windows (reference
    _LanguageModelDataset + _WikiText behavior)."""

    def __init__(self, root, segment, seq_len, namespace, vocab=None):
        self._root = os.path.expanduser(root)
        self._segment = segment
        self._seq_len = seq_len
        self._namespace = namespace
        path = self._find_file()
        tokens = self._tokenize(path)
        if vocab is None:
            counter = _text.utils.count_tokens_from_str(' '.join(tokens))
            vocab = _text.vocab.Vocabulary(counter, most_freq_count=None,
                                           min_freq=1)
        # shared across segments: pass the train split's vocabulary when
        # building validation/test so token ids line up (reference
        # _LanguageModelDataset vocab parameter)
        self.vocabulary = vocab
        ids = onp.asarray(self.vocabulary.to_indices(tokens),
                          dtype=onp.int32)
        n = (len(ids) - 1) // seq_len
        data = ids[:n * seq_len].reshape(n, seq_len)
        target = ids[1:n * seq_len + 1].reshape(n, seq_len)
        super().__init__(list(zip(data, target)))

    def _find_file(self):
        for name in (f'wiki.{self._segment}.tokens',
                     f'{self._segment}.txt'):
            p = os.path.join(self._root, name)
            if os.path.exists(p):
                return p
        raise FileNotFoundError(
            f'{self._namespace}: no local data under {self._root!r} '
            f'(zero-egress build — place wiki.{self._segment}.tokens '
            'there; the reference would download it)')

    @staticmethod
    def _tokenize(path):
        with open(path, encoding='utf-8') as f:
            return f.read().replace('\n', ' <eos> ').split()


class WikiText2(_LanguageModelDataset):
    """WikiText-2 (reference contrib/data/text.py:WikiText2)."""

    def __init__(self, root='~/.mxnet/datasets/wikitext-2',
                 segment='train', seq_len=35, vocab=None):
        super().__init__(root, segment, seq_len, 'wikitext-2',
                         vocab=vocab)


class WikiText103(_LanguageModelDataset):
    """WikiText-103 (reference contrib/data/text.py:WikiText103)."""

    def __init__(self, root='~/.mxnet/datasets/wikitext-103',
                 segment='train', seq_len=35, vocab=None):
        super().__init__(root, segment, seq_len, 'wikitext-103',
                         vocab=vocab)
