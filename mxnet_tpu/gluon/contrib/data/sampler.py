"""Contrib samplers (reference
``python/mxnet/gluon/contrib/data/sampler.py``)."""

from ...data.sampler import Sampler

__all__ = ['IntervalSampler']


class IntervalSampler(Sampler):
    """Sample i, i+interval, i+2*interval, ... then roll to i+1
    (reference IntervalSampler — truncated-BPTT batch layout)."""

    def __init__(self, length, interval, rollover=True):
        if interval > length:
            raise ValueError(
                f'interval {interval} must be <= length {length}')
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            yield from range(i, self._length, self._interval)

    def __len__(self):
        return self._length if self._rollover else \
            len(range(0, self._length, self._interval))
