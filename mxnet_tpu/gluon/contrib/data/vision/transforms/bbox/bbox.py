"""Joint image+bbox transform blocks (reference
``python/mxnet/gluon/contrib/data/vision/transforms/bbox/bbox.py``).

Each block takes ``(image HWC, bbox (N, 4+))`` and returns the
transformed pair — the detection-pipeline counterpart of the plain
vision transforms. Image math runs through ``mx.np``; box math is the
host-side utils module (tiny arrays, pipeline stage).
"""

import random as _random

import numpy as onp

from mxnet_tpu.ndarray.ndarray import NDArray, array
from mxnet_tpu.gluon.block import Block

from . import utils

__all__ = ['ImageBboxRandomFlipLeftRight', 'ImageBboxCrop',
           'ImageBboxRandomCropWithConstraints', 'ImageBboxRandomExpand',
           'ImageBboxResize']


def _hw(img):
    return img.shape[0], img.shape[1]


class ImageBboxRandomFlipLeftRight(Block):
    """Flip image+boxes horizontally with probability p (reference
    ImageBboxRandomFlipLeftRight)."""

    def __init__(self, p=0.5, **kwargs):
        super().__init__(**kwargs)
        self.p = p

    def forward(self, img, bbox):
        if _random.random() < self.p:
            img = img[:, ::-1, :]
            h, w = _hw(img)
            bbox = array(utils.bbox_flip(
                bbox.asnumpy() if isinstance(bbox, NDArray) else bbox,
                (w, h), flip_x=True))
        return img, bbox


class ImageBboxCrop(Block):
    """Fixed crop (x, y, w, h) of image+boxes (reference ImageBboxCrop)."""

    def __init__(self, crop, allow_outside_center=False, **kwargs):
        super().__init__(**kwargs)
        self._crop = crop
        self._allow = allow_outside_center

    def forward(self, img, bbox):
        x, y, w, h = self._crop
        img = img[y:y + h, x:x + w, :]
        raw = bbox.asnumpy() if isinstance(bbox, NDArray) else bbox
        return img, array(utils.bbox_crop(raw, (x, y, w, h),
                                          self._allow))


class ImageBboxRandomCropWithConstraints(Block):
    """SSD-style constrained random crop (reference
    ImageBboxRandomCropWithConstraints)."""

    def __init__(self, min_scale=0.3, max_scale=1.0, max_aspect_ratio=2,
                 constraints=None, max_trial=50, **kwargs):
        super().__init__(**kwargs)
        self._kw = dict(min_scale=min_scale, max_scale=max_scale,
                        max_aspect_ratio=max_aspect_ratio,
                        constraints=constraints, max_trial=max_trial)

    def forward(self, img, bbox):
        h, w = _hw(img)
        raw = bbox.asnumpy() if isinstance(bbox, NDArray) else bbox
        new_bbox, crop = utils.bbox_random_crop_with_constraints(
            raw, (w, h), **self._kw)
        x, y, cw, ch = crop
        return img[y:y + ch, x:x + cw, :], array(new_bbox)


class ImageBboxRandomExpand(Block):
    """Place the image on a larger mean-filled canvas, shifting boxes
    (reference ImageBboxRandomExpand — the SSD zoom-out augment)."""

    def __init__(self, max_ratio=4, fill=0, keep_ratio=True, **kwargs):
        super().__init__(**kwargs)
        self._max_ratio = max_ratio
        self._fill = fill
        self._keep = keep_ratio

    def forward(self, img, bbox):
        if self._max_ratio <= 1:
            return img, bbox
        h, w = _hw(img)
        ratio_x = _random.uniform(1, self._max_ratio)
        ratio_y = ratio_x if self._keep else _random.uniform(
            1, self._max_ratio)
        oh, ow = int(h * ratio_y), int(w * ratio_x)
        off_y = _random.randint(0, oh - h)
        off_x = _random.randint(0, ow - w)
        raw_img = img.asnumpy() if isinstance(img, NDArray) else \
            onp.asarray(img)
        canvas = onp.full((oh, ow, raw_img.shape[-1]), self._fill,
                          raw_img.dtype)
        canvas[off_y:off_y + h, off_x:off_x + w, :] = raw_img
        raw = bbox.asnumpy() if isinstance(bbox, NDArray) else bbox
        return array(canvas), array(utils.bbox_translate(
            raw, x_offset=off_x, y_offset=off_y))


class ImageBboxResize(Block):
    """Resize image+boxes to (width, height) (reference
    ImageBboxResize)."""

    def __init__(self, width, height, interpolation=1, **kwargs):
        super().__init__(**kwargs)
        self._size = (width, height)
        self._interp = interpolation

    def forward(self, img, bbox):
        h, w = _hw(img)
        from mxnet_tpu.image import imresize
        img = imresize(img if isinstance(img, NDArray) else array(img),
                       self._size[0], self._size[1],
                       interp=self._interp)
        raw = bbox.asnumpy() if isinstance(bbox, NDArray) else bbox
        return img, array(utils.bbox_resize(raw, (w, h), self._size))
