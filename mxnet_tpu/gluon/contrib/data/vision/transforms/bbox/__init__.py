"""Image+bbox joint transforms (reference
python/mxnet/gluon/contrib/data/vision/transforms/bbox/__init__.py)."""

from .bbox import *
from . import utils
