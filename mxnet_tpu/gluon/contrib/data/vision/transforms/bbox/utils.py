"""Bounding-box transform utilities (reference
``python/mxnet/gluon/contrib/data/vision/transforms/bbox/utils.py``).

Host-side numpy math: these run in the data pipeline before batches
reach the device (boxes are tiny; shipping them through XLA per sample
would cost more in dispatch than compute). Boxes are ``(N, 4+)`` arrays
in corner ``xmin, ymin, xmax, ymax`` layout unless stated otherwise.
"""

import random as _random

import numpy as np

__all__ = ['bbox_crop', 'bbox_flip', 'bbox_resize', 'bbox_translate',
           'bbox_iou', 'bbox_xywh_to_xyxy', 'bbox_xyxy_to_xywh',
           'bbox_clip_xyxy', 'bbox_random_crop_with_constraints']


def _check(bbox):
    bbox = np.asarray(bbox, np.float32)
    if bbox.ndim != 2 or bbox.shape[1] < 4:
        raise ValueError(f'bbox must be (N, 4+), got {bbox.shape}')
    return bbox


def bbox_crop(bbox, crop_box=None, allow_outside_center=True):
    """Crop boxes to a window, dropping the ones that vanish
    (reference utils.bbox_crop)."""
    bbox = _check(bbox).copy()
    if crop_box is None:
        return bbox
    if sum(c is None for c in crop_box) == 4:
        return bbox
    l, t, w, h = crop_box
    left = l or 0
    top = t or 0
    right = left + (w or np.inf)
    bottom = top + (h or np.inf)
    window = np.array([left, top, right, bottom], np.float32)
    if allow_outside_center:
        mask = np.ones(bbox.shape[0], dtype=bool)
    else:
        centers = (bbox[:, :2] + bbox[:, 2:4]) / 2
        mask = np.logical_and(window[:2] <= centers,
                              centers < window[2:]).all(axis=1)
    bbox[:, :2] = np.maximum(bbox[:, :2], window[:2])
    bbox[:, 2:4] = np.minimum(bbox[:, 2:4], window[2:])
    bbox[:, :2] -= window[:2]
    bbox[:, 2:4] -= window[:2]
    mask = np.logical_and(mask, (bbox[:, :2] < bbox[:, 2:4]).all(axis=1))
    return bbox[mask]


def bbox_flip(bbox, size, flip_x=False, flip_y=False):
    """Mirror boxes inside a (width, height) canvas (reference
    utils.bbox_flip)."""
    bbox = _check(bbox).copy()
    width, height = size
    if flip_x:
        xmax = width - bbox[:, 0]
        xmin = width - bbox[:, 2]
        bbox[:, 0], bbox[:, 2] = xmin, xmax
    if flip_y:
        ymax = height - bbox[:, 1]
        ymin = height - bbox[:, 3]
        bbox[:, 1], bbox[:, 3] = ymin, ymax
    return bbox


def bbox_resize(bbox, in_size, out_size):
    """Rescale boxes from in_size (w, h) to out_size (reference
    utils.bbox_resize)."""
    bbox = _check(bbox).copy()
    sx = out_size[0] / in_size[0]
    sy = out_size[1] / in_size[1]
    bbox[:, [0, 2]] *= sx
    bbox[:, [1, 3]] *= sy
    return bbox


def bbox_translate(bbox, x_offset=0, y_offset=0):
    bbox = _check(bbox).copy()
    bbox[:, [0, 2]] += float(x_offset)
    bbox[:, [1, 3]] += float(y_offset)
    return bbox


def bbox_iou(bbox_a, bbox_b, offset=0):
    """Pairwise IoU matrix (N, M) (reference utils.bbox_iou)."""
    a = np.asarray(bbox_a, np.float32)
    b = np.asarray(bbox_b, np.float32)
    tl = np.maximum(a[:, None, :2], b[None, :, :2])
    br = np.minimum(a[:, None, 2:4], b[None, :, 2:4])
    inter = np.prod(np.maximum(br - tl + offset, 0), axis=2)
    area_a = np.prod(a[:, 2:4] - a[:, :2] + offset, axis=1)
    area_b = np.prod(b[:, 2:4] - b[:, :2] + offset, axis=1)
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / np.maximum(union, 1e-12)


def bbox_xywh_to_xyxy(xywh):
    x = np.asarray(xywh, np.float32)
    out = x.copy()
    out[..., 2] = x[..., 0] + np.maximum(0, x[..., 2] - 1)
    out[..., 3] = x[..., 1] + np.maximum(0, x[..., 3] - 1)
    return out


def bbox_xyxy_to_xywh(xyxy):
    x = np.asarray(xyxy, np.float32)
    out = x.copy()
    out[..., 2] = x[..., 2] - x[..., 0] + 1
    out[..., 3] = x[..., 3] - x[..., 1] + 1
    return out


def bbox_clip_xyxy(xyxy, width, height):
    x = np.asarray(xyxy, np.float32).copy()
    x[..., 0] = np.clip(x[..., 0], 0, width - 1)
    x[..., 1] = np.clip(x[..., 1], 0, height - 1)
    x[..., 2] = np.clip(x[..., 2], 0, width - 1)
    x[..., 3] = np.clip(x[..., 3], 0, height - 1)
    return x


def bbox_random_crop_with_constraints(bbox, size, min_scale=0.3,
                                      max_scale=1, max_aspect_ratio=2,
                                      constraints=None, max_trial=50):
    """SSD-style constrained random crop (reference
    utils.bbox_random_crop_with_constraints): sample candidate windows
    until one satisfies a minimum-IoU constraint with some box."""
    if constraints is None:
        constraints = ((0.1, None), (0.3, None), (0.5, None),
                       (0.7, None), (0.9, None), (None, 1))
    w, h = size
    bbox = _check(bbox)
    candidates = [(0, 0, w, h)]
    for min_iou, max_iou in constraints:
        min_iou = -np.inf if min_iou is None else min_iou
        max_iou = np.inf if max_iou is None else max_iou
        for _ in range(max_trial):
            scale = _random.uniform(min_scale, max_scale)
            aspect = _random.uniform(
                max(1 / max_aspect_ratio, scale * scale),
                min(max_aspect_ratio, 1 / (scale * scale)))
            crop_h = int(h * scale / np.sqrt(aspect))
            crop_w = int(w * scale * np.sqrt(aspect))
            if crop_w > w or crop_h > h:
                continue
            crop_t = _random.randrange(h - crop_h + 1)
            crop_l = _random.randrange(w - crop_w + 1)
            crop_bb = np.array([[crop_l, crop_t, crop_l + crop_w,
                                 crop_t + crop_h]], np.float32)
            if len(bbox) == 0:
                return bbox, (crop_l, crop_t, crop_w, crop_h)
            iou = bbox_iou(bbox, crop_bb)
            if min_iou <= iou.min() and iou.max() <= max_iou:
                candidates.append((crop_l, crop_t, crop_w, crop_h))
                break
    # pick a candidate that keeps at least one box
    while candidates:
        crop = candidates.pop(int(_random.random()
                                  * len(candidates)))
        new_bbox = bbox_crop(bbox, crop, allow_outside_center=False)
        if len(new_bbox):
            return new_bbox, crop
    return bbox, (0, 0, w, h)
