"""Contrib vision transforms (reference
python/mxnet/gluon/contrib/data/vision/transforms/__init__.py)."""

from . import bbox
