"""Contrib vision data (reference
python/mxnet/gluon/contrib/data/vision/__init__.py)."""

from . import transforms
