"""Contrib datasets/samplers (reference
python/mxnet/gluon/contrib/data/__init__.py)."""

from .sampler import IntervalSampler
from . import text
from . import vision
