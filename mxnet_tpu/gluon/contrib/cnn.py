"""Contrib CNN layers (reference python/mxnet/gluon/contrib/cnn/conv_layers.py).

DeformableConvolution: the data-dependent sampling is expressed as bilinear
gathers (XLA gather), replacing the hand-written CUDA kernel
(src/operator/contrib/deformable_convolution.cu).
"""

import jax.numpy as jnp

from ..block import HybridBlock
from ..parameter import Parameter
from ..nn import Conv2D
from ...ndarray.ndarray import NDArray
from ...ops.registry import Op, apply_op


class DeformableConvolution(HybridBlock):
    """Deformable conv v1 (reference contrib/cnn/conv_layers.py:44)."""

    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, use_bias=True, in_channels=0,
                 activation=None, weight_initializer=None,
                 bias_initializer='zeros',
                 offset_weight_initializer='zeros',
                 offset_bias_initializer='zeros', **kwargs):
        super().__init__(**kwargs)
        k = kernel_size if isinstance(kernel_size, tuple) else \
            (kernel_size, kernel_size)
        self._k = k
        self._strides = strides if isinstance(strides, tuple) else \
            (strides, strides)
        self._padding = padding if isinstance(padding, tuple) else \
            (padding, padding)
        self._channels = channels
        self._use_bias = use_bias
        self.offset_conv = Conv2D(
            2 * k[0] * k[1] * num_deformable_group, kernel_size=k,
            strides=self._strides, padding=self._padding,
            weight_initializer=offset_weight_initializer,
            bias_initializer=offset_bias_initializer)
        self.weight = Parameter('weight',
                                shape=(channels, in_channels, *k),
                                init=weight_initializer,
                                allow_deferred_init=True)
        if use_bias:
            self.bias = Parameter('bias', shape=(channels,),
                                  init=bias_initializer,
                                  allow_deferred_init=True)

    def forward(self, x):
        offsets = self.offset_conv(x)
        if self.weight.shape[1] == 0:
            w = list(self.weight.shape)
            w[1] = x.shape[1]
            self.weight.shape = tuple(w)
            self.weight._finish_deferred_init()
        if self._use_bias and self.bias._data is None:
            self.bias._finish_deferred_init()
        arrays = [x, offsets, self.weight.data()] + (
            [self.bias.data()] if self._use_bias else [])
        kh, kw = self._k
        sh, sw = self._strides
        ph, pw = self._padding

        def fn(xr, off, w, *b):
            n, c, h, wd = xr.shape
            oh, ow = off.shape[2], off.shape[3]
            xp = jnp.pad(xr, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
            # base sampling grid per kernel tap
            ys = jnp.arange(oh) * sh
            xs = jnp.arange(ow) * sw
            out = jnp.zeros((n, self._channels, oh, ow), xr.dtype)
            cols = []
            for i in range(kh):
                for j in range(kw):
                    t = i * kw + j
                    dy = off[:, 2 * t]
                    dx = off[:, 2 * t + 1]
                    yy = ys[None, :, None] + i + dy
                    xx = xs[None, None, :] + j + dx
                    y0 = jnp.clip(jnp.floor(yy), 0, h + 2 * ph - 2)
                    x0 = jnp.clip(jnp.floor(xx), 0, wd + 2 * pw - 2)
                    wy = yy - y0
                    wx = xx - x0
                    y0 = y0.astype(jnp.int32)
                    x0 = x0.astype(jnp.int32)
                    bidx = jnp.arange(n)[:, None, None]
                    v = (xp[bidx, :, y0, x0] * ((1 - wy) * (1 - wx))[..., None]
                         + xp[bidx, :, y0 + 1, x0] * (wy * (1 - wx))[..., None]
                         + xp[bidx, :, y0, x0 + 1] * ((1 - wy) * wx)[..., None]
                         + xp[bidx, :, y0 + 1, x0 + 1] * (wy * wx)[..., None])
                    cols.append(v)  # (n, oh, ow, c)
            col = jnp.stack(cols, axis=-1)  # (n, oh, ow, c, kh*kw)
            col = col.reshape(n, oh, ow, c * kh * kw)
            wmat = w.reshape(self._channels, c * kh * kw)
            out = jnp.einsum('nhwk,ok->nohw', col, wmat)
            if b:
                out = out + b[0][None, :, None, None]
            return out

        op = Op('deformable_convolution', fn, differentiable=True)
        return apply_op(op, arrays, fn, name='deformable_convolution')
