"""``gluon.contrib`` (reference python/mxnet/gluon/contrib/)."""

from . import estimator
from . import cnn
from . import rnn
from . import nn
from . import data
