"""Contrib layers (reference
``python/mxnet/gluon/contrib/nn/basic_layers.py``: Concurrent,
HybridConcurrent, Identity, SparseEmbedding, PixelShuffle1D/2D/3D;
SyncBatchNorm lives in the main ``gluon.nn`` here)."""

from ... import nn
from ...block import Block, HybridBlock
from .... import numpy as np

__all__ = ['Concurrent', 'HybridConcurrent', 'Identity',
           'SparseEmbedding', 'PixelShuffle1D', 'PixelShuffle2D',
           'PixelShuffle3D']


class Concurrent(nn.Sequential):
    """Run children on the same input, concat outputs along `axis`
    (reference contrib/nn/basic_layers.py:Concurrent)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def forward(self, x):
        out = [block(x) for block in self._children.values()]
        return np.concatenate(out, axis=self.axis)


class HybridConcurrent(nn.HybridSequential):
    """Hybridizable Concurrent (reference HybridConcurrent)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def forward(self, x):
        out = [block(x) for block in self._children.values()]
        return np.concatenate(out, axis=self.axis)


class Identity(HybridBlock):
    """Pass-through block (reference Identity) — the placeholder arm of
    a Concurrent."""

    def forward(self, x):
        return x


class SparseEmbedding(Block):
    """Embedding whose gradient is row-sparse (reference
    SparseEmbedding, backed by Embedding(sparse_grad=True) here): only
    rows referenced by the batch receive updates when the optimizer
    supports lazy/sparse updates."""

    def __init__(self, input_dim, output_dim, dtype='float32',
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._embed = nn.Embedding(input_dim, output_dim, dtype=dtype,
                                   weight_initializer=weight_initializer,
                                   sparse_grad=True)
        self.weight = self._embed.weight

    def forward(self, x):
        return self._embed(x)

    def __repr__(self):
        return (f'SparseEmbedding({self._embed._input_dim} -> '
                f'{self._embed._output_dim})')


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, dims, **kwargs):
        super().__init__(**kwargs)
        self._factors = (factor,) * dims if isinstance(factor, int) \
            else tuple(factor)
        assert len(self._factors) == dims


class PixelShuffle1D(_PixelShuffle):
    r"""(N, C·f, W) → (N, C, W·f) sub-pixel upsample (reference
    PixelShuffle1D; Shi et al. 2016)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)

    def forward(self, x):
        (f,) = self._factors
        N, C, W = x.shape
        x = x.reshape(N, C // f, f, W)
        x = x.transpose(0, 1, 3, 2)
        return x.reshape(N, C // f, W * f)


class PixelShuffle2D(_PixelShuffle):
    r"""(N, C·f1·f2, H, W) → (N, C, H·f1, W·f2) (reference
    PixelShuffle2D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)

    def forward(self, x):
        f1, f2 = self._factors
        N, C, H, W = x.shape
        c = C // (f1 * f2)
        x = x.reshape(N, c, f1, f2, H, W)
        x = x.transpose(0, 1, 4, 2, 5, 3)
        return x.reshape(N, c, H * f1, W * f2)


class PixelShuffle3D(_PixelShuffle):
    r"""(N, C·f1·f2·f3, D, H, W) → (N, C, D·f1, H·f2, W·f3) (reference
    PixelShuffle3D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)

    def forward(self, x):
        f1, f2, f3 = self._factors
        N, C, D, H, W = x.shape
        c = C // (f1 * f2 * f3)
        x = x.reshape(N, c, f1, f2, f3, D, H, W)
        x = x.transpose(0, 1, 5, 2, 6, 3, 7, 4)
        return x.reshape(N, c, D * f1, H * f2, W * f3)
