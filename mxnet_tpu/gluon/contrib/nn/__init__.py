"""Contrib layers (reference
``python/mxnet/gluon/contrib/nn/__init__.py``)."""

from .basic_layers import *
from ...nn import SyncBatchNorm  # reference keeps it here; main nn owns it
