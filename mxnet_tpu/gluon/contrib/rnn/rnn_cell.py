"""Contrib recurrent cells (reference
``python/mxnet/gluon/contrib/rnn/rnn_cell.py`` — VariationalDropoutCell
and LSTMPCell)."""

from ...parameter import Parameter
from ...rnn.rnn_cell import ModifierCell, RecurrentCell, _op
from .... import _tape

__all__ = ['VariationalDropoutCell', 'LSTMPCell']


class VariationalDropoutCell(ModifierCell):
    """Variational (locked) dropout (Gal & Ghahramani): ONE Bernoulli
    mask per sequence, reused at every timestep for inputs/states/
    outputs (reference contrib/rnn/rnn_cell.py:VariationalDropoutCell).
    Masks regenerate on ``reset()``."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0, **kwargs):
        super().__init__(base_cell, **kwargs)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _mask(self, cached, p, like):
        if p == 0.0 or not _tape.is_training():
            return cached, None
        if cached is None or cached.shape != like.shape:
            keep = _op('random_bernoulli', prob=1 - p, size=like.shape)
            cached = keep / (1 - p)
        return cached, cached

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None, valid_length=None):
        """Fresh masks per sequence: the reference's unroll resets
        before stepping, so each minibatch gets its own locked mask."""
        self.reset()
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs,
                              valid_length=valid_length)

    def forward(self, inputs, states):
        self._input_mask, m = self._mask(self._input_mask,
                                         self.drop_inputs, inputs)
        if m is not None:
            inputs = inputs * m
        if self.drop_states and states:
            self._state_mask, m = self._mask(self._state_mask,
                                             self.drop_states, states[0])
            if m is not None:
                states = [states[0] * m] + list(states[1:])
        out, next_states = self.base_cell(inputs, states)
        self._output_mask, m = self._mask(self._output_mask,
                                          self.drop_outputs, out)
        if m is not None:
            out = out * m
        return out, next_states

    def __repr__(self):
        return (f'VariationalDropoutCell(p_out={self.drop_outputs}, '
                f'p_state={self.drop_states})')


class LSTMPCell(RecurrentCell):
    """LSTM with a projected hidden state (Sak et al. 2014; reference
    contrib/rnn/rnn_cell.py:LSTMPCell): the recurrent/output state is
    ``r = h2r(o * tanh(c))`` of size ``projection_size`` — smaller
    recurrent matmuls for large hidden sizes, a shape the MXU likes.

    States: [r (B, projection_size), c (B, hidden_size)].
    """

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None,
                 i2h_bias_initializer='zeros', h2h_bias_initializer='zeros',
                 input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self.i2h_weight = Parameter('i2h_weight',
                                    shape=(4 * hidden_size, input_size),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter(
            'h2h_weight', shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer)
        self.h2r_weight = Parameter(
            'h2r_weight', shape=(projection_size, hidden_size),
            init=h2r_weight_initializer)
        self.i2h_bias = Parameter('i2h_bias', shape=(4 * hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter('h2h_bias', shape=(4 * hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{'shape': (batch_size, self._projection_size)},
                {'shape': (batch_size, self._hidden_size)}]

    def _infer(self, x):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])
            self.i2h_weight._finish_deferred_init()

    def forward(self, inputs, states):
        self._infer(inputs)
        h = self._hidden_size
        gates = _op('fully_connected', inputs, self.i2h_weight.data(),
                    self.i2h_bias.data(), num_hidden=4 * h) + \
            _op('fully_connected', states[0], self.h2h_weight.data(),
                self.h2h_bias.data(), num_hidden=4 * h)
        i = _op('sigmoid', gates[:, :h])
        f = _op('sigmoid', gates[:, h:2 * h])
        g = _op('tanh', gates[:, 2 * h:3 * h])
        o = _op('sigmoid', gates[:, 3 * h:])
        c = f * states[1] + i * g
        hidden = o * _op('tanh', c)
        r = _op('fully_connected', hidden, self.h2r_weight.data(), None,
                num_hidden=self._projection_size, no_bias=True)
        return r, [r, c]
