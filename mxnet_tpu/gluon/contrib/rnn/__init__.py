"""Contrib RNN cells (reference
``python/mxnet/gluon/contrib/rnn/__init__.py``)."""

from .conv_rnn_cell import *
from .rnn_cell import *
