"""Convolutional recurrent cells (reference
``python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py`` —
Conv{RNN,LSTM,GRU}Cell for 1D/2D/3D inputs).

TPU design: each timestep is two convolutions (input→hidden,
hidden→hidden) + gate math; under ``unroll`` the whole sequence becomes
one traced graph, so XLA batches the convs onto the MXU and fuses the
gate elementwise ops — no per-step dispatch.
"""

from ...rnn.rnn_cell import RecurrentCell, _op


def _tuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _BaseConvRNNCell(RecurrentCell):
    """Shared conv machinery. `input_shape` is (C, spatial...) without
    the batch axis; `dims` = number of spatial dims."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                 i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, dims,
                 conv_layout, activation, **kwargs):
        super().__init__(**kwargs)
        default_layout = 'NC' + 'DHW'[3 - dims:]
        if conv_layout != default_layout:
            raise ValueError(
                f'only {default_layout!r} conv_layout is supported '
                f'(channels-first is the TPU-native layout; got '
                f'{conv_layout!r})')
        self._input_shape = tuple(input_shape)
        self._hidden_channels = hidden_channels
        self._dims = dims
        self._activation = activation
        self._i2h_kernel = _tuple(i2h_kernel, dims)
        self._h2h_kernel = _tuple(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise ValueError(
                    f'h2h_kernel must be odd to keep spatial dims, got '
                    f'{self._h2h_kernel}')
        self._i2h_pad = _tuple(i2h_pad, dims)
        self._i2h_dilate = _tuple(i2h_dilate, dims)
        self._h2h_dilate = _tuple(h2h_dilate, dims)
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))

        from ...parameter import Parameter
        ng = self._num_gates
        in_c = self._input_shape[0]
        self.i2h_weight = Parameter(
            'i2h_weight',
            shape=(ng * hidden_channels, in_c) + self._i2h_kernel,
            init=i2h_weight_initializer)
        self.h2h_weight = Parameter(
            'h2h_weight',
            shape=(ng * hidden_channels, hidden_channels)
            + self._h2h_kernel,
            init=h2h_weight_initializer)
        self.i2h_bias = Parameter('i2h_bias',
                                  shape=(ng * hidden_channels,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter('h2h_bias',
                                  shape=(ng * hidden_channels,),
                                  init=h2h_bias_initializer)

    @property
    def _num_gates(self):
        raise NotImplementedError

    def _state_shape(self):
        # i2h output spatial dims define the state spatial dims
        spatial = []
        for i, s in enumerate(self._input_shape[1:]):
            k, p, d = (self._i2h_kernel[i], self._i2h_pad[i],
                       self._i2h_dilate[i])
            spatial.append((s + 2 * p - d * (k - 1) - 1) + 1)
        return (self._hidden_channels,) + tuple(spatial)

    def state_info(self, batch_size=0):
        shape = (batch_size,) + self._state_shape()
        return [{'shape': shape} for _ in range(self._num_states)]

    @property
    def _num_states(self):
        return 1

    def _convs(self, inputs, state):
        ng = self._num_gates
        i2h = _op('convolution', inputs, self.i2h_weight.data(),
                  self.i2h_bias.data(), kernel=self._i2h_kernel,
                  pad=self._i2h_pad, dilate=self._i2h_dilate,
                  num_filter=ng * self._hidden_channels)
        h2h = _op('convolution', state, self.h2h_weight.data(),
                  self.h2h_bias.data(), kernel=self._h2h_kernel,
                  pad=self._h2h_pad, dilate=self._h2h_dilate,
                  num_filter=ng * self._hidden_channels)
        return i2h, h2h

    def _act(self, x):
        return _op('activation', x, act_type=self._activation)


class _ConvRNNCell(_BaseConvRNNCell):
    _num_gates = 1

    def forward(self, inputs, states):
        i2h, h2h = self._convs(inputs, states[0])
        out = self._act(i2h + h2h)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    _num_gates = 4
    _num_states = 2

    def forward(self, inputs, states):
        i2h, h2h = self._convs(inputs, states[0])
        gates = i2h + h2h
        c = self._hidden_channels
        sl = [slice(None)] * gates.ndim
        def g(j):
            sl[1] = slice(j * c, (j + 1) * c)
            return gates[tuple(sl)]
        i = _op('sigmoid', g(0))
        f = _op('sigmoid', g(1))
        gg = self._act(g(2))
        o = _op('sigmoid', g(3))
        next_c = f * states[1] + i * gg
        out = o * self._act(next_c)
        return out, [out, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _num_gates = 3

    def forward(self, inputs, states):
        i2h, h2h = self._convs(inputs, states[0])
        c = self._hidden_channels
        sl = [slice(None)] * i2h.ndim
        def g(x, j):
            sl[1] = slice(j * c, (j + 1) * c)
            return x[tuple(sl)]
        r = _op('sigmoid', g(i2h, 0) + g(h2h, 0))
        z = _op('sigmoid', g(i2h, 1) + g(h2h, 1))
        n = self._act(g(i2h, 2) + r * g(h2h, 2))
        out = (1 - z) * n + z * states[0]
        return out, [out]


def _make(base, dims, name, doc):
    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                     i2h_weight_initializer=None,
                     h2h_weight_initializer=None,
                     i2h_bias_initializer='zeros',
                     h2h_bias_initializer='zeros',
                     conv_layout='NC' + 'DHW'[3 - dims:],
                     activation='tanh', **kwargs):
            super().__init__(
                input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                i2h_pad, i2h_dilate, h2h_dilate, i2h_weight_initializer,
                h2h_weight_initializer, i2h_bias_initializer,
                h2h_bias_initializer, dims, conv_layout, activation,
                **kwargs)

    Cell.__name__ = Cell.__qualname__ = name
    Cell.__doc__ = doc
    return Cell


_REF = ('reference python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py')
Conv1DRNNCell = _make(_ConvRNNCell, 1, 'Conv1DRNNCell',
                      f'1D convolutional RNN cell ({_REF}).')
Conv2DRNNCell = _make(_ConvRNNCell, 2, 'Conv2DRNNCell',
                      f'2D convolutional RNN cell ({_REF}).')
Conv3DRNNCell = _make(_ConvRNNCell, 3, 'Conv3DRNNCell',
                      f'3D convolutional RNN cell ({_REF}).')
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, 'Conv1DLSTMCell',
                       f'1D ConvLSTM cell (Shi et al.; {_REF}).')
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, 'Conv2DLSTMCell',
                       f'2D ConvLSTM cell (Shi et al.; {_REF}).')
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, 'Conv3DLSTMCell',
                       f'3D ConvLSTM cell (Shi et al.; {_REF}).')
Conv1DGRUCell = _make(_ConvGRUCell, 1, 'Conv1DGRUCell',
                      f'1D convolutional GRU cell ({_REF}).')
Conv2DGRUCell = _make(_ConvGRUCell, 2, 'Conv2DGRUCell',
                      f'2D convolutional GRU cell ({_REF}).')
Conv3DGRUCell = _make(_ConvGRUCell, 3, 'Conv3DGRUCell',
                      f'3D convolutional GRU cell ({_REF}).')

__all__ = ['Conv1DRNNCell', 'Conv2DRNNCell', 'Conv3DRNNCell',
           'Conv1DLSTMCell', 'Conv2DLSTMCell', 'Conv3DLSTMCell',
           'Conv1DGRUCell', 'Conv2DGRUCell', 'Conv3DGRUCell']
