"""Estimator (reference
python/mxnet/gluon/contrib/estimator/estimator.py): a fit() loop over
DataLoaders with event handlers."""

from ....context import current_context
from ....metric import Accuracy, EvalMetric, Loss as LossMetric
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler, StoppingHandler,
                            TrainBegin, TrainEnd, ValidationHandler)


class Estimator:
    """Reference estimator.py:Estimator."""

    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None, devices=None,
                 batch_processor=None):
        from .batch_processor import BatchProcessor
        self.net = net
        self.loss = loss
        self.batch_processor = batch_processor or BatchProcessor()
        tm = train_metrics or [Accuracy()]
        if not isinstance(tm, list):
            tm = [tm]
        # copy: never mutate the caller's list (and never double-append a
        # loss metric when the same list builds two estimators)
        self.train_metrics = list(tm) + [LossMetric(name='train loss')]
        self.val_metrics = val_metrics or []
        self.context = context or devices or [current_context()]
        if not isinstance(self.context, list):
            self.context = [self.context]
        self.trainer = trainer
        self.max_epoch = None

    def prepare_loss_and_metrics(self):
        return self.train_metrics, self.val_metrics

    def evaluate(self, val_data=None, batch_axis=0):
        from ....metric import Loss as LossMetric
        for metric in self.val_metrics:
            metric.reset()
        for batch in val_data or []:
            data, label, pred, loss = \
                self.batch_processor.evaluate_batch(self, batch,
                                                    batch_axis)
            for metric in self.val_metrics:
                if isinstance(metric, LossMetric):
                    metric.update(0, loss)
                else:
                    metric.update(label, pred)

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        from ...trainer import Trainer

        self.max_epoch = epochs or 1
        if self.trainer is None:
            self.trainer = Trainer(self.net.collect_params(), 'adam')

        handlers = self._init_handlers(val_data, event_handlers, batches)
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = self._categorize(handlers)
        # ANY handler may request a stop (EarlyStoppingHandler etc.), not
        # just the auto-added StoppingHandler
        def _should_stop():
            return any(getattr(h, 'stop_training', False) for h in handlers)

        for h in train_begin:
            h.train_begin(self)
        while not _should_stop():
            for h in epoch_begin:
                h.epoch_begin(self)
            for batch in train_data:
                for h in batch_begin:
                    h.batch_begin(self, batch=batch)
                data, label, pred, loss = \
                    self.batch_processor.fit_batch(self, batch,
                                                   batch_axis)
                self.trainer.step(data.shape[batch_axis])
                for h in batch_end:
                    h.batch_end(self, batch=batch, pred=pred, label=label,
                                loss=loss, batch_size=data.shape[batch_axis])
                if _should_stop():
                    break
            for h in epoch_end:
                h.epoch_end(self)
        for h in train_end:
            h.train_end(self)

    def _init_handlers(self, val_data, event_handlers, batches):
        handlers = list(event_handlers or [])
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            handlers.append(StoppingHandler(self.max_epoch, batches))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(self.train_metrics))
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in handlers):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(metrics=self.train_metrics))
        return handlers

    def _categorize(self, handlers):
        return ([h for h in handlers if isinstance(h, TrainBegin)],
                [h for h in handlers if isinstance(h, EpochBegin)],
                [h for h in handlers if isinstance(h, BatchBegin)],
                [h for h in handlers if isinstance(h, BatchEnd)],
                [h for h in handlers if isinstance(h, EpochEnd)],
                [h for h in handlers if isinstance(h, TrainEnd)])
