"""Estimator event handlers (reference
python/mxnet/gluon/contrib/estimator/event_handler.py — epoch/batch events,
checkpointing, early stopping)."""

import logging
import os
import time

import numpy as _np


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max epoch/batch (reference event_handler.py:StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.max_epoch = self.max_epoch or estimator.max_epoch
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch == self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch == self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Update training metrics per batch."""

    def __init__(self, metrics, priority=-1000):
        self.metrics = metrics or []
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get('pred')
        label = kwargs.get('label')
        loss = kwargs.get('loss')
        from ....metric import Loss as LossMetric
        for metric in self.metrics:
            if isinstance(metric, LossMetric):
                metric.update(0, loss)
            else:
                metric.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    """Reference event_handler.py:LoggingHandler."""

    def __init__(self, log_interval='epoch', metrics=None, priority=_np.inf):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.priority = priority
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        logging.info('Training begin')

    def train_end(self, estimator, *args, **kwargs):
        train_time = time.time() - self.train_start
        logging.info('Train finished using total %ds', train_time)

    def epoch_begin(self, estimator, *args, **kwargs):
        if self.log_interval is not None:
            self.epoch_start = time.time()

    def epoch_end(self, estimator, *args, **kwargs):
        if self.log_interval is not None:
            msg = f'[Epoch {self.current_epoch}] finished in ' \
                f'{time.time() - self.epoch_start:.3f}s: '
            for metric in self.metrics:
                name, value = metric.get()
                msg += f'{name}: {value:.4f}, '
            logging.info(msg.rstrip(', '))
        self.current_epoch += 1
        self.batch_index = 0

    def batch_end(self, estimator, *args, **kwargs):
        if isinstance(self.log_interval, int):
            batch_size = kwargs.get('batch_size', 0)
            self.processed_samples += batch_size
            if self.batch_index % self.log_interval == 0:
                msg = f'[Epoch {self.current_epoch}][Batch ' \
                    f'{self.batch_index}] '
                for metric in self.metrics:
                    name, value = metric.get()
                    msg += f'{name}: {value:.4f}, '
                logging.info(msg.rstrip(', '))
        self.batch_index += 1


def _resolve_mode(mode, monitor):
    """'auto' infers the comparison direction from the monitor's name
    (reference event_handler.py: acc/f1/topk-style metrics maximize)."""
    if mode != 'auto':
        return mode
    name = getattr(monitor, 'name', str(monitor) if monitor else '') or ''
    name = name.lower()
    maximize = any(t in name for t in
                   ('acc', 'f1', 'mcc', 'auc', 'map', 'topk', 'pearson'))
    return 'max' if maximize else 'min'


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Periodic / best-k checkpointing (reference
    event_handler.py:CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix='model', monitor=None,
                 verbose=0, save_best=False, mode='auto', epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_epoch = 0
        self.current_batch = 0
        self.mode = _resolve_mode(mode, monitor)
        self.best = -_np.inf if self.mode == 'max' else _np.inf
        os.makedirs(model_dir, exist_ok=True)

    def train_begin(self, estimator, *args, **kwargs):
        self.current_epoch = 0
        self.current_batch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator)
            if self.save_best and self.monitor is not None:
                name, value = self.monitor.get()
                improved = value > self.best if self.mode == 'max' else \
                    value < self.best
                if improved:
                    self.best = value
                    estimator.net.save_parameters(os.path.join(
                        self.model_dir, f'{self.model_prefix}-best.params.npz'))

    def _save(self, estimator):
        prefix = os.path.join(self.model_dir, self.model_prefix)
        estimator.net.save_parameters(
            f'{prefix}-epoch{self.current_epoch}.params.npz')
        if estimator.trainer is not None:
            estimator.trainer.save_states(
                f'{prefix}-epoch{self.current_epoch}.states')


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Reference event_handler.py:EarlyStoppingHandler."""

    def __init__(self, monitor, min_delta=0, patience=0, mode='auto',
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.mode = _resolve_mode(mode, monitor)
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.best = self.baseline if self.baseline is not None else (
            -_np.inf if self.mode == 'max' else _np.inf)

    def epoch_end(self, estimator, *args, **kwargs):
        name, value = self.monitor.get()
        if self.mode == 'max':
            improved = value > self.best + self.min_delta
        else:
            improved = value < self.best - self.min_delta
        if improved:
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                self.stop_training = True
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch > 0:
            logging.info('Epoch %d: early stopping', self.stopped_epoch)
