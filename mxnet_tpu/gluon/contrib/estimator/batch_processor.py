"""BatchProcessor (reference
``python/mxnet/gluon/contrib/estimator/batch_processor.py``) — the
per-batch fit/evaluate strategy object, overridable for non-standard
batch layouts (multi-input models, custom losses)."""

from .... import autograd

__all__ = ['BatchProcessor']


class BatchProcessor:
    """Default single-data/single-label batch processing."""

    def _get_data_and_label(self, batch, ctx=None, batch_axis=0):
        return batch[0], batch[1]

    def evaluate_batch(self, estimator, val_batch, batch_axis=0):
        """Returns (data, label, pred, loss) for one validation batch
        (reference BatchProcessor.evaluate_batch)."""
        data, label = self._get_data_and_label(val_batch)
        pred = estimator.net(data)
        loss = estimator.loss(pred, label)
        return data, label, pred, loss

    def fit_batch(self, estimator, train_batch, batch_axis=0):
        """Forward + backward for one train batch; the Estimator owns
        the trainer.step (reference BatchProcessor.fit_batch)."""
        data, label = self._get_data_and_label(train_batch)
        with autograd.record():
            pred = estimator.net(data)
            loss = estimator.loss(pred, label)
        loss.backward()
        return data, label, pred, loss
