"""Gluon Parameter & Constant.

Reference: ``python/mxnet/gluon/parameter.py`` (Parameter:47, deferred init
``_finish_deferred_init``:336, per-ctx data/grad replicas ``data``:567
``grad``:604, Constant:708). Semantics preserved: shape may contain unknown
dims (0/-1) resolved at first forward; ``initialize`` places replicas on one
or more Contexts; ``attach_grad`` allocates grad buffers and marks the data
arrays as autograd variables.
"""

import numpy as _np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray.ndarray import NDArray, array
from .. import initializer


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape was known (reference
    parameter.py:DeferredInitializationError)."""


class Parameter:
    """A trainable parameter (reference gluon/parameter.py:47)."""

    def __init__(self, name='weight', grad_req='write', shape=None,
                 dtype='float32', lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype='default', grad_stype='default'):
        self._name = name
        self._grad_req = grad_req if differentiable else 'null'
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self._allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None   # dict Context -> NDArray
        self._grad = None   # dict Context -> NDArray
        self._deferred_init = None
        self._structure_name = None  # set by Block registration
        # PartitionSpec matched by the mx.sharding rule registry when a
        # mesh context compiled this param's block; placement is sticky:
        # set_data() re-places new values (checkpoint restores) on the
        # same mesh layout instead of silently un-sharding the param
        self._sharding_spec = None
        self._sharding_mesh = None

    # ------------------------------------------------------------------ props
    @property
    def name(self):
        return self._structure_name or self._name

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown_ok = all(
            s1 in (0, -1, None) or s1 == s2
            for s1, s2 in zip(self._shape, new_shape))
        assert len(self._shape) == len(new_shape) and unknown_ok, (
            f'Expected shape {self._shape} is incompatible with given shape '
            f'{new_shape} for Parameter {self.name}')
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ('write', 'add', 'null')
        if not self._differentiable:
            req = 'null'
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == 'null':
            self._grad = None
            if self._data:
                for arr in self._data.values():
                    arr._ag = None
        elif self._data is not None:
            self._init_grad()

    @property
    def stype(self):
        return self._stype

    def _shape_known(self):
        return self._shape is not None and all(
            s not in (0, -1, None) and s > 0 for s in self._shape)

    # ------------------------------------------------------------------- init
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Reference parameter.py:initialize. Deferred if shape unknown and
        allow_deferred_init."""
        if self._data is not None and not force_reinit:
            return
        default_init = default_init or initializer.Uniform()
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if not self._shape_known():
            if self._allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise ValueError(
                f'Cannot initialize Parameter {self.name} because it has '
                f'invalid shape: {self._shape}.')
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        init = init or self.init or default_init
        if isinstance(init, str):
            init = initializer.create(init)
        host = _np.zeros(self._shape, dtype=self.dtype)
        proto = array(host, ctx=ctx[0], dtype=self.dtype)
        desc = initializer.InitDesc(self.name, {'__init__': ''})
        if isinstance(init, initializer.Initializer):
            init(desc, proto)
        else:
            init(proto)
        self._data = {c: (proto if c == ctx[0]
                          else proto.as_in_context(c)) for c in ctx}
        self._deferred_init = None
        if self._grad_req != 'null':
            self._init_grad()

    def _finish_deferred_init(self):
        """Reference parameter.py:336 — called once the shape is inferred."""
        if self._deferred_init is None:
            return
        if not self._shape_known():
            raise DeferredInitializationError(
                f'Parameter {self.name} has unknown shape {self._shape}')
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _init_grad(self):
        from .. import _tape
        import jax.numpy as jnp
        self._grad = {}
        for c, arr in self._data.items():
            g = NDArray(jnp.zeros(arr.shape, dtype=arr._data.dtype), ctx=c)
            self._grad[c] = g
            _tape.mark_variables([arr], [g], [self._grad_req])

    # ------------------------------------------------------------------ access
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f'Parameter {self.name} has not been initialized yet '
                    'because initialization was deferred. Actual '
                    'initialization happens during the first forward pass.')
            raise RuntimeError(
                f'Parameter {self.name} has not been initialized. You '
                'should initialize parameters and create Trainer with '
                'Block.collect_params() instead of Block.params')

    def data(self, ctx=None):
        """Reference parameter.py:567."""
        self._check_initialized()
        if ctx is None:
            return next(iter(self._data.values()))
        if ctx not in self._data:
            raise RuntimeError(
                f'Parameter {self.name} was not initialized on context '
                f'{ctx}. It was only initialized on {list(self._data)}.')
        return self._data[ctx]

    def list_data(self):
        self._check_initialized()
        return list(self._data.values())

    @staticmethod
    def _surface_grad(g):
        """Row-sparse grads ride on the buffer as ``_rsp`` (written by
        the tape's sparse-embedding backward) — surface them so the
        dense table-shaped buffer is never materialized."""
        rsp = getattr(g, '_rsp', None)
        return rsp if rsp is not None else g

    def grad(self, ctx=None):
        """Reference parameter.py:604."""
        self._check_initialized()
        if self._grad is None:
            raise RuntimeError(
                f'Cannot get gradient array for Parameter {self.name} '
                'because grad_req="null"')
        if ctx is None:
            return self._surface_grad(next(iter(self._grad.values())))
        return self._surface_grad(self._grad[ctx])

    def list_grad(self):
        self._check_initialized()
        if self._grad is None:
            return []
        return [self._surface_grad(g) for g in self._grad.values()]

    def list_ctx(self):
        if self._data is None and self._deferred_init is not None:
            return self._deferred_init[1]
        self._check_initialized()
        return list(self._data)

    def set_data(self, data):
        """Set value on all contexts (reference parameter.py:set_data)."""
        self.shape = data.shape
        if self._data is None:
            if self._deferred_init is not None:
                self._finish_deferred_init()
            else:
                self._data = {data.context if isinstance(data, NDArray)
                              else current_context(): None}
        src = data if isinstance(data, NDArray) else array(data)
        for c in list(self._data):
            self._data[c] = src.as_in_context(c).astype(self.dtype,
                                                        copy=False)
        if self._sharding_spec is not None and \
                self._sharding_mesh is not None:
            # sticky sharded placement: a restored checkpoint value goes
            # back onto the mesh layout the compiled program expects
            import jax
            from jax.sharding import NamedSharding
            sh = NamedSharding(self._sharding_mesh, self._sharding_spec)
            for c, nd in list(self._data.items()):
                if getattr(nd._data, 'sharding', None) != sh:
                    nd._rebind(jax.device_put(nd._data, sh))
        if self._grad_req != 'null':
            self._init_grad()

    def zero_grad(self):
        if self._grad is None:
            return
        import jax.numpy as jnp
        for g in self._grad.values():
            g._rebind(jnp.zeros_like(g._data))
            g._rsp = None   # clear any surfaced row-sparse gradient

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            proto = next(iter(self._data.values()))
            self._data = {c: proto.as_in_context(c) for c in ctx}
            if self._grad_req != 'null':
                self._init_grad()
        elif self._deferred_init is not None:
            init, _, default_init = self._deferred_init
            self._deferred_init = (init, ctx, default_init)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        for c, arr in self._data.items():
            self._data[c] = arr.astype(dtype)
        if self._grad_req != 'null':
            self._init_grad()

    def var(self):
        raise NotImplementedError(
            'Symbol variables do not exist in the TPU design; use '
            'HybridBlock.export for graph capture')

    def __repr__(self):
        return (f'Parameter {self.name} (shape={self._shape}, '
                f'dtype={self.dtype})')


class Constant(Parameter):
    """Non-differentiable constant parameter (reference parameter.py:708)."""

    def __init__(self, value, name='const'):
        if not isinstance(value, NDArray):
            value = array(value)
        self._value = value
        super().__init__(name=name, grad_req='null', shape=value.shape,
                         dtype=value.dtype, differentiable=False,
                         init=None)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._data = {c: self._value.as_in_context(c) for c in ctx}
