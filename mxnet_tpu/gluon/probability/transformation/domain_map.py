"""Constraint → transformation registries (reference
``python/mxnet/gluon/probability/transformation/domain_map.py`` —
``biject_to``/``transform_to`` map a constraint object to a bijection
from unconstrained reals onto that domain; used by variational
inference to optimize constrained parameters freely)."""

from .transformation import (ComposeTransform, ExpTransform,
                             AffineTransform, SigmoidTransform,
                             SoftmaxTransform, StickBreakingTransform,
                             LowerCholeskyTransform)
from ..distributions import constraint as C

__all__ = ['biject_to', 'transform_to', 'domain_map']


class domain_map:
    """Decorator-based registry dispatching on constraint type."""

    def __init__(self):
        self._registry = {}

    def register(self, constraint_type, factory=None):
        if factory is None:
            return lambda f: self.register(constraint_type, f)
        self._registry[constraint_type] = factory
        return factory

    def __call__(self, cons):
        for typ in type(cons).__mro__:
            if typ in self._registry:
                return self._registry[typ](cons)
        raise NotImplementedError(
            f'no transform registered for constraint {cons!r}')


biject_to = domain_map()
transform_to = domain_map()


@biject_to.register(C.Positive)
@transform_to.register(C.Positive)
def _positive(cons):
    return ExpTransform()


@biject_to.register(C.NonNegative)
@transform_to.register(C.NonNegative)
def _nonnegative(cons):
    return ExpTransform()


@biject_to.register(C.GreaterThan)
@transform_to.register(C.GreaterThan)
@biject_to.register(C.GreaterThanEq)
@transform_to.register(C.GreaterThanEq)
def _greater_than(cons):
    return ComposeTransform([ExpTransform(),
                             AffineTransform(cons._low, 1.0)])


@biject_to.register(C.LessThan)
@transform_to.register(C.LessThan)
@biject_to.register(C.LessThanEq)
@transform_to.register(C.LessThanEq)
def _less_than(cons):
    return ComposeTransform([ExpTransform(),
                             AffineTransform(cons._high, -1.0)])


@biject_to.register(C.Interval)
@transform_to.register(C.Interval)
@biject_to.register(C.OpenInterval)
@transform_to.register(C.OpenInterval)
@biject_to.register(C.HalfOpenInterval)
@transform_to.register(C.HalfOpenInterval)
@biject_to.register(C.UnitInterval)
@transform_to.register(C.UnitInterval)
def _interval(cons):
    low, high = cons._low, cons._high
    return ComposeTransform([SigmoidTransform(),
                             AffineTransform(low, high - low)])


@biject_to.register(C.Real)
@transform_to.register(C.Real)
def _real(cons):
    return AffineTransform(0.0, 1.0)


@transform_to.register(C.Simplex)
def _simplex(cons):
    return SoftmaxTransform()


@biject_to.register(C.Simplex)
def _simplex_bijective(cons):
    return StickBreakingTransform()


@biject_to.register(C.LowerCholesky)
@transform_to.register(C.LowerCholesky)
def _lower_cholesky(cons):
    return LowerCholeskyTransform()
