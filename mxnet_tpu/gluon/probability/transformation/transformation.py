"""Invertible transformations with log-det-Jacobian tracking.

Reference:
``python/mxnet/gluon/probability/transformation/transformation.py``
(Transformation/ComposeTransform/Exp/Affine/Power/Sigmoid/Softmax/Abs +
TransformBlock). Each transform is pure NDArray math — differentiable
through the tape and traceable under hybridize/jit.
"""

from .... import numpy as np
from .... import numpy_extension as npx
from ..distributions import constraint
from ..distributions.utils import as_array, sum_right_most
from ...block import HybridBlock

__all__ = ['Transformation', 'TransformBlock', 'ComposeTransform',
           'ExpTransform', 'AffineTransform', 'PowerTransform',
           'SigmoidTransform', 'SoftmaxTransform', 'AbsTransform',
           'StickBreakingTransform', 'LowerCholeskyTransform']


class Transformation:
    r"""y = T(x); carries T^{-1} and log|det dT/dx|."""

    bijective = False
    event_dim = 0

    @property
    def sign(self):
        """Sign of the Jacobian determinant (monotone transforms)."""
        raise NotImplementedError

    def __call__(self, x):
        return self._forward_compute(x)

    def _forward_compute(self, x):
        raise NotImplementedError

    def _inverse_compute(self, y):
        raise NotImplementedError

    def log_det_jacobian(self, x, y):
        raise NotImplementedError

    @property
    def inv(self):
        return _InverseTransformation(self)


class _InverseTransformation(Transformation):
    """The inverse of a transformation (reference
    _InverseTransformation)."""

    def __init__(self, forward_transformation):
        self._inst = forward_transformation

    @property
    def inv(self):
        return self._inst

    @property
    def sign(self):
        return self._inst.sign

    @property
    def event_dim(self):
        return self._inst.event_dim

    def __call__(self, x):
        return self._inst._inverse_compute(x)

    def log_det_jacobian(self, x, y):
        return -self._inst.log_det_jacobian(y, x)


class TransformBlock(Transformation, HybridBlock):
    """A transformation that is also a gluon block — lets transforms own
    Parameters (e.g. learned flows), reference TransformBlock."""

    def __init__(self, **kwargs):
        HybridBlock.__init__(self, **kwargs)


class ComposeTransform(Transformation):
    def __init__(self, parts):
        self._parts = list(parts)

    @property
    def event_dim(self):
        return max(p.event_dim for p in self._parts)

    def _forward_compute(self, x):
        for p in self._parts:
            x = p(x)
        return x

    def _inverse_compute(self, y):
        for p in reversed(self._parts):
            y = p.inv(y)
        return y

    @property
    def inv(self):
        return ComposeTransform([p.inv for p in reversed(self._parts)])

    def log_det_jacobian(self, x, y):
        result = 0.0
        event_dim = self.event_dim
        xs = [x]
        for p in self._parts[:-1]:
            xs.append(p(xs[-1]))
        xs.append(y)
        for p, x0, y0 in zip(self._parts, xs[:-1], xs[1:]):
            term = p.log_det_jacobian(x0, y0)
            term = sum_right_most(term, event_dim - p.event_dim)
            result = result + term
        return result


class ExpTransform(Transformation):
    bijective = True
    sign = 1

    def _forward_compute(self, x):
        return np.exp(x)

    def _inverse_compute(self, y):
        return np.log(y)

    def log_det_jacobian(self, x, y):
        return x


class AffineTransform(Transformation):
    """y = loc + scale * x."""

    bijective = True

    def __init__(self, loc, scale, event_dim=0):
        self.loc = as_array(loc)
        self.scale = as_array(scale)
        self.event_dim = event_dim

    @property
    def sign(self):
        return np.sign(self.scale)

    def _forward_compute(self, x):
        return self.loc + self.scale * x

    def _inverse_compute(self, y):
        return (y - self.loc) / self.scale

    def log_det_jacobian(self, x, y):
        abs_log = np.log(np.abs(self.scale)) * np.ones_like(x)
        return sum_right_most(abs_log, self.event_dim)


class PowerTransform(Transformation):
    """y = x ** exponent (on positives)."""

    bijective = True
    sign = 1

    def __init__(self, exponent):
        self.exponent = as_array(exponent)

    def _forward_compute(self, x):
        return x ** self.exponent

    def _inverse_compute(self, y):
        return y ** (1 / self.exponent)

    def log_det_jacobian(self, x, y):
        return np.log(np.abs(self.exponent * y / x))


class SigmoidTransform(Transformation):
    bijective = True
    sign = 1

    def _forward_compute(self, x):
        return npx.sigmoid(x)

    def _inverse_compute(self, y):
        return np.log(y) - np.log1p(-y)

    def log_det_jacobian(self, x, y):
        return -npx.softplus(-x) - npx.softplus(x)


class SoftmaxTransform(Transformation):
    """y = softmax(x) — not bijective (projects to the simplex)."""

    event_dim = 1

    def _forward_compute(self, x):
        return npx.softmax(x, axis=-1)

    def _inverse_compute(self, y):
        return np.log(y)


class AbsTransform(Transformation):
    def _forward_compute(self, x):
        return np.abs(x)

    def _inverse_compute(self, y):
        return y


class StickBreakingTransform(Transformation):
    """Bijection R^{K-1} → interior of the K-simplex via stick-breaking
    (the `biject_to(Simplex)` map): z_k = sigmoid(x_k − log(K−1−k)),
    y_k = z_k ∏_{j<k}(1−z_j), y_K = remainder."""

    bijective = True
    event_dim = 1
    sign = 1

    @staticmethod
    def _offset(k_minus_1):
        return np.log(np.arange(float(k_minus_1), 0.0, -1.0))

    def _forward_compute(self, x):
        k1 = x.shape[-1]
        z = npx.sigmoid(x - self._offset(k1))
        # remainder after each stick break: r_k = prod_{j<k} (1-z_j)
        log1mz = np.log1p(-z)
        r = np.exp(np.cumsum(log1mz, axis=-1))
        r_prev = np.concatenate(
            [np.ones_like(r[..., :1]), r[..., :-1]], axis=-1)
        head = z * r_prev
        tail = r[..., -1:]
        return np.concatenate([head, tail], axis=-1)

    def _inverse_compute(self, y):
        k1 = y.shape[-1] - 1
        head = y[..., :-1]
        csum = np.cumsum(head, axis=-1)
        r_prev = 1 - np.concatenate(
            [np.zeros_like(csum[..., :1]), csum[..., :-1]], axis=-1)
        z = head / r_prev
        return np.log(z) - np.log1p(-z) + self._offset(k1)

    def log_det_jacobian(self, x, y):
        # |det| = prod_k z_k (1-z_k) r_k with r_k = 1 - cumsum(y)_{k-1}
        k1 = x.shape[-1]
        u = x - self._offset(k1)
        head = y[..., :-1]
        csum = np.cumsum(head, axis=-1)
        r_prev = 1 - np.concatenate(
            [np.zeros_like(csum[..., :1]), csum[..., :-1]], axis=-1)
        return (-npx.softplus(u) - npx.softplus(-u)
                + np.log(r_prev)).sum(-1)


class LowerCholeskyTransform(Transformation):
    """Unconstrained square matrix → lower-triangular with positive
    diagonal (the `biject_to(LowerCholesky)` map): keep the strict lower
    triangle, exponentiate the diagonal."""

    bijective = True
    event_dim = 2
    sign = 1

    def _forward_compute(self, x):
        diag = np.diagonal(x, axis1=-2, axis2=-1)
        eye = np.eye(x.shape[-1])
        return np.tril(x, -1) + np.exp(diag)[..., None] * eye

    def _inverse_compute(self, y):
        diag = np.diagonal(y, axis1=-2, axis2=-1)
        eye = np.eye(y.shape[-1])
        return np.tril(y, -1) + np.log(diag)[..., None] * eye

    def log_det_jacobian(self, x, y):
        return np.diagonal(x, axis1=-2, axis2=-1).sum(-1)
