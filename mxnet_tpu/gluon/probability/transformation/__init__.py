"""Transformations (reference
``python/mxnet/gluon/probability/transformation/__init__.py``)."""

from .transformation import *
from .domain_map import *
