"""``mx.gluon.probability`` — distributions, transformations, KL
registry, and stochastic blocks.

Reference: ``python/mxnet/gluon/probability/__init__.py`` (5.5 kLoC
package: 25+ distributions, biject_to/transform_to domain maps,
StochasticBlock). TPU-native re-design: every density/statistic is pure
``mx.np`` math over jax (differentiable through the tape, traceable
under hybridize/jit), sampling draws from the Context-scoped PRNG, and
the gamma family gets pathwise gradients through an
implicit-reparameterized sampler op instead of the reference's
score-function fallback.
"""

from .distributions import *
from .transformation import *
from .block import *
