"""Stochastic blocks (reference
``python/mxnet/gluon/probability/block/__init__.py``)."""

from .stochastic_block import *
