"""StochasticBlock — HybridBlock with in-forward loss accumulation.

Reference: ``python/mxnet/gluon/probability/block/stochastic_block.py``
(StochasticBlock.collectLoss decorator + add_loss + .losses;
StochasticSequential). Used for Bayesian layers where the objective is
task loss + accumulated KL terms. Works under hybridize: the decorated
forward returns ``(out, losses)``, so the captured jit graph carries the
loss tensors as extra outputs — the same trick the reference plays with
CachedOp multi-outputs.
"""

from functools import wraps

from ...block import HybridBlock

__all__ = ['StochasticBlock', 'StochasticSequential']


class StochasticBlock(HybridBlock):

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._losses = []
        self._losscache = []
        self._flag = False  # whether collectLoss ran this call

    def add_loss(self, loss):
        self._losscache.append(loss)

    @staticmethod
    def collectLoss(func):
        """Decorate ``forward`` so losses added via ``add_loss`` during
        the call are returned alongside the output."""

        @wraps(func)
        def inner(self, *args, **kwargs):
            func_out = func(self, *args, **kwargs)
            collected_loss = self._losscache
            self._losscache = []
            self._flag = True
            return (func_out, collected_loss)

        return inner

    def __call__(self, *args, **kwargs):
        self._flag = False
        out = super().__call__(*args, **kwargs)
        if not self._flag:
            raise ValueError('The forward function should be decorated by '
                             'StochasticBlock.collectLoss')
        self._losses = out[1]
        return out[0]

    @property
    def losses(self):
        return self._losses


class StochasticSequential(StochasticBlock):
    """Stack StochasticBlocks sequentially (reference
    StochasticSequential)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._layers = []

    def add(self, *blocks):
        for block in blocks:
            self._layers.append(block)
            self.register_child(block)

    @StochasticBlock.collectLoss
    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = []
            if isinstance(x, (tuple, list)):
                args = x[1:]
                x = x[0]
        if args:
            x = tuple([x] + list(args))
        for block in self._layers:
            if hasattr(block, '_losses'):
                self.add_loss(block._losses)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)
