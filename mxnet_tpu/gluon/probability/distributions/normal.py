"""Normal distribution (reference
``python/mxnet/gluon/probability/distributions/normal.py``)."""

import math

from .... import numpy as np
from .exp_family import ExponentialFamily
from .constraint import Real, Positive
from .utils import as_array, erf, erfinv

__all__ = ['Normal']

_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


class Normal(ExponentialFamily):
    has_grad = True
    support = Real()
    arg_constraints = {'loc': Real(), 'scale': Positive()}

    def __init__(self, loc=0.0, scale=1.0, F=None, validate_args=None):
        self.loc = as_array(loc)
        self.scale = as_array(scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return (self.loc + self.scale).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        z = (value - self.loc) / self.scale
        return -0.5 * z ** 2 - np.log(self.scale) - _HALF_LOG_2PI

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        eps = np.random.normal(0.0, 1.0, shape)
        return self.loc + self.scale * eps

    def sample_n(self, size=None):
        from .utils import sample_n_shape_converter
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'loc', 'scale')

    def cdf(self, value):
        return 0.5 * (1 + erf((value - self.loc) /
                              (self.scale * math.sqrt(2))))

    def icdf(self, value):
        return self.loc + self.scale * math.sqrt(2) * erfinv(2 * value - 1)

    @property
    def mean(self):
        return self.loc * np.ones_like(self.scale)

    @property
    def stddev(self):
        return self.scale * np.ones_like(self.loc)

    @property
    def variance(self):
        return self.stddev ** 2

    def entropy(self):
        return 0.5 + _HALF_LOG_2PI + np.log(self.scale * np.ones_like(
            self.loc))

    @property
    def _natural_params(self):
        return (self.loc / self.scale ** 2, -0.5 / self.scale ** 2)

    def _log_normalizer(self, x, y):
        return -0.25 * x ** 2 / y + 0.5 * np.log(-math.pi / y)
