"""Distribution base class.

Reference: ``python/mxnet/gluon/probability/distributions/distribution.py``
(Distribution: log_prob/pdf/cdf/icdf/sample/sample_n/broadcast_to/
enumerate_support/mean/variance/stddev/support/entropy/perplexity).

TPU-native notes: one array namespace (mx.np over jax) — the reference's
``F`` mode switch is accepted and ignored; every method is pure NDArray
math, so log_prob/entropy differentiate through the autograd tape and the
whole object works under ``hybridize``/jit tracing. Sampling draws keys
from the Context-scoped PRNG resource (mxnet_tpu/_rng.py), never from
user-managed key plumbing.
"""

from .... import numpy as np

__all__ = ['Distribution']


class Distribution:
    """Base class for probability distributions."""

    # whether `sample()` is reparameterized (pathwise gradients flow to
    # the distribution parameters)
    has_grad = False
    has_enumerate_support = False
    arg_constraints = {}
    _validate_args = False

    @staticmethod
    def set_default_validate_args(value):
        if value not in (True, False):
            raise ValueError
        Distribution._validate_args = value

    def __init__(self, F=None, event_dim=None, validate_args=None):
        self.F = F or np
        self.event_dim = event_dim
        if validate_args is not None:
            self._validate_args = validate_args
        if self._validate_args:
            for param, constraint in self.arg_constraints.items():
                if param not in self.__dict__:
                    continue  # lazily-derived parameter
                constraint.check(getattr(self, param))
        super().__init__()

    # ----------------------------------------------------------- densities
    def log_prob(self, value):
        raise NotImplementedError

    def pdf(self, value):
        return np.exp(self.log_prob(value))

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    # ------------------------------------------------------------ sampling
    def sample(self, size=None):
        """Draw a sample of shape `size` (None → broadcasted batch
        shape). `size` must include the batch shape, numpy-style."""
        raise NotImplementedError

    def sample_n(self, size=None):
        """Draw samples with an iid prefix of shape `size` prepended to
        the batch shape (reference sample_n)."""
        raise NotImplementedError

    def broadcast_to(self, batch_shape):
        """Return a new distribution with parameters broadcast to
        `batch_shape` (reference Distribution.broadcast_to)."""
        raise NotImplementedError

    def enumerate_support(self):
        raise NotImplementedError

    # ---------------------------------------------------------- statistics
    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return np.sqrt(self.variance)

    @property
    def support(self):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def perplexity(self):
        return np.exp(self.entropy())

    # ------------------------------------------------------------- helpers
    def _validate_samples(self, value):
        return self.support.check(value)

    def __repr__(self):
        args = ', '.join(
            f'{p}={getattr(self, p)!r}' for p in self.arg_constraints
            if p in self.__dict__)
        return f'{type(self).__name__}({args})'

    def _broadcast_args(self, batch_shape, *names):
        """Shared broadcast_to body: returns a shallow copy with the
        named parameters broadcast."""
        import copy
        new = copy.copy(self)
        for n in names:
            v = getattr(self, n)
            if v is not None:
                setattr(new, n, np.broadcast_to(v, batch_shape))
        return new
