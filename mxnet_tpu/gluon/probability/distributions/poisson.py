"""Poisson distribution (reference
``python/mxnet/gluon/probability/distributions/poisson.py``)."""

from .... import numpy as np
from .exp_family import ExponentialFamily
from .constraint import Positive, NonNegativeInteger
from .utils import as_array, sample_n_shape_converter, gammaln

__all__ = ['Poisson']


class Poisson(ExponentialFamily):
    support = NonNegativeInteger()
    arg_constraints = {'rate': Positive()}

    def __init__(self, rate=1.0, F=None, validate_args=None):
        self.rate = as_array(rate)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.rate.shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        return (value * np.log(self.rate) - self.rate
                - gammaln(value + 1))

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        return np.random.poisson(self.rate, shape).astype('float32')

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'rate')

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    @property
    def _natural_params(self):
        return (np.log(self.rate),)

    def _log_normalizer(self, x):
        return np.exp(x)
