"""Relaxed one-hot categorical / Concrete distribution (reference
``python/mxnet/gluon/probability/distributions/relaxed_one_hot_categorical.py``
— Gumbel-softmax reparameterization, Jang et al. / Maddison et al.)."""

from .... import numpy as np
from .... import numpy_extension as npx
from .distribution import Distribution
from .constraint import Simplex, Real
from .utils import (as_array, cached_property, prob2logit, logit2prob,
                    sample_n_shape_converter, gammaln, sum_right_most)

__all__ = ['RelaxedOneHotCategorical']


class RelaxedOneHotCategorical(Distribution):
    has_grad = True
    support = Simplex()
    arg_constraints = {'prob': Simplex(), 'logit': Real()}

    def __init__(self, T, num_events, prob=None, logit=None, F=None,
                 validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError(
                'Either `prob` or `logit` must be specified, but not both.')
        self.T = as_array(T)
        self.num_events = int(num_events)
        if prob is not None:
            self.prob = as_array(prob)
        else:
            self.logit = as_array(logit)
        super().__init__(F=F, event_dim=1, validate_args=validate_args)

    @cached_property
    def prob(self):
        return logit2prob(self.logit, False)

    @cached_property
    def logit(self):
        return prob2logit(self.prob, False)

    def _batch_shape(self):
        p = self.__dict__.get('prob')
        return (p if p is not None else self.logit).shape[:-1]

    def sample(self, size=None):
        full = (tuple(size) + (self.num_events,)) if size is not None \
            else self.logit.shape
        u = np.clip(np.random.uniform(0.0, 1.0, full), 1e-7, 1 - 1e-7)
        gumbel = -np.log(-np.log(u))
        return npx.softmax((self.logit + gumbel) / self.T, axis=-1)

    def sample_n(self, size=None):
        full = sample_n_shape_converter(size) + self._batch_shape()
        return self.sample(full)

    def broadcast_to(self, batch_shape):
        import copy
        new = copy.copy(self)
        full = tuple(batch_shape) + (self.num_events,)
        if 'prob' in self.__dict__:
            new.prob = np.broadcast_to(self.prob, full)
            new.__dict__.pop('logit', None)
        else:
            new.logit = np.broadcast_to(self.logit, full)
            new.__dict__.pop('prob', None)
        return new

    def log_prob(self, value):
        """Concrete density (Maddison et al., eq. 10):
        log((K−1)!) + (K−1) log λ + Σ(log α_i − (λ+1) log y_i)
        − K·logsumexp(log α − λ log y)."""
        if self._validate_args:
            self._validate_samples(value)
        k = self.num_events
        lam = self.T
        logits = npx.log_softmax(self.logit, axis=-1)
        ly = np.log(value)
        score = logits - lam * ly
        m = score.max(-1, keepdims=True)
        lse = (m + np.log(np.exp(score - m).sum(-1, keepdims=True)))
        lse = lse.squeeze(-1)
        return (gammaln(np.array(float(k))) + (k - 1) * np.log(lam)
                + sum_right_most(logits - (lam + 1) * ly, 1)
                - k * lse)

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError
