"""Student's t distribution (reference
``python/mxnet/gluon/probability/distributions/studentT.py``)."""

import math

from .... import numpy as np
from .distribution import Distribution
from .constraint import Real, Positive
from .utils import (as_array, sample_n_shape_converter, gammaln, digamma,
                    rgamma)

__all__ = ['StudentT']


class StudentT(Distribution):
    has_grad = True
    support = Real()
    arg_constraints = {'df': Positive(), 'loc': Real(),
                       'scale': Positive()}

    def __init__(self, df, loc=0.0, scale=1.0, F=None, validate_args=None):
        self.df = as_array(df)
        self.loc = as_array(loc)
        self.scale = as_array(scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return (self.df + self.loc + self.scale).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        nu = self.df
        z = (value - self.loc) / self.scale
        return (gammaln((nu + 1) / 2) - gammaln(nu / 2)
                - 0.5 * np.log(nu * math.pi) - np.log(self.scale)
                - (nu + 1) / 2 * np.log1p(z ** 2 / nu))

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        ones = np.ones(shape) if shape else np.array(1.0)
        nu = np.broadcast_to(self.df * ones, shape)
        eps = np.random.normal(0.0, 1.0, shape)
        chi2 = rgamma(nu / 2, shape) * 2
        return self.loc + self.scale * eps / np.sqrt(chi2 / nu)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'df', 'loc', 'scale')

    @property
    def mean(self):
        m = self.loc * np.ones_like(self.df + self.scale)
        return np.where(self.df > 1, m, np.full(m.shape, float('nan')))

    @property
    def variance(self):
        nu = self.df
        v = self.scale ** 2 * nu / (nu - 2)
        inf = np.full(v.shape, float('inf'))
        nan = np.full(v.shape, float('nan'))
        return np.where(nu > 2, v, np.where(nu > 1, inf, nan))

    def entropy(self):
        # (nu+1)/2 (psi((nu+1)/2)-psi(nu/2)) + log(sqrt(nu) B(nu/2, 1/2))
        nu = self.df
        half = (nu + 1) / 2
        lbeta = (gammaln(nu / 2) + 0.5 * math.log(math.pi)
                 - gammaln(half))
        return (half * (digamma(half) - digamma(nu / 2))
                + 0.5 * np.log(nu) + lbeta
                + np.log(self.scale) * np.ones_like(nu))
