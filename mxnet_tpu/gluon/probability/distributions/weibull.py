"""Weibull distribution (reference
``python/mxnet/gluon/probability/distributions/weibull.py``)."""

from .... import numpy as np
from .distribution import Distribution
from .constraint import Positive
from .utils import as_array, sample_n_shape_converter, EULER

__all__ = ['Weibull']


class Weibull(Distribution):
    has_grad = True
    support = Positive()
    arg_constraints = {'concentration': Positive(), 'scale': Positive()}

    def __init__(self, concentration, scale=1.0, F=None,
                 validate_args=None):
        self.concentration = as_array(concentration)
        self.scale = as_array(scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return (self.concentration + self.scale).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        k, lam = self.concentration, self.scale
        z = value / lam
        return (np.log(k / lam) + (k - 1) * np.log(z) - z ** k)

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        u = np.random.uniform(0.0, 1.0, shape)
        return self.scale * (-np.log1p(-u)) ** (1 / self.concentration)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'concentration', 'scale')

    def cdf(self, value):
        return -np.expm1(-(value / self.scale) ** self.concentration)

    def icdf(self, value):
        return self.scale * (-np.log1p(-value)) ** (1 / self.concentration)

    @property
    def mean(self):
        from .utils import gammaln
        return self.scale * np.exp(gammaln(1 + 1 / self.concentration))

    @property
    def variance(self):
        from .utils import gammaln
        g1 = np.exp(gammaln(1 + 1 / self.concentration))
        g2 = np.exp(gammaln(1 + 2 / self.concentration))
        return self.scale ** 2 * (g2 - g1 ** 2)

    def entropy(self):
        k, lam = self.concentration, self.scale
        return EULER * (1 - 1 / k) + np.log(lam / k) + 1
