"""Cauchy distribution (reference
``python/mxnet/gluon/probability/distributions/cauchy.py``)."""

import math

from .... import numpy as np
from .distribution import Distribution
from .constraint import Real, Positive
from .utils import as_array, sample_n_shape_converter

__all__ = ['Cauchy']


class Cauchy(Distribution):
    has_grad = True
    support = Real()
    arg_constraints = {'loc': Real(), 'scale': Positive()}

    def __init__(self, loc=0.0, scale=1.0, F=None, validate_args=None):
        self.loc = as_array(loc)
        self.scale = as_array(scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return (self.loc + self.scale).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        z = (value - self.loc) / self.scale
        return (-math.log(math.pi) - np.log(self.scale)
                - np.log1p(z ** 2))

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        # inverse-CDF reparameterization
        u = np.random.uniform(0.0, 1.0, shape)
        return self.loc + self.scale * np.tan(math.pi * (u - 0.5))

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'loc', 'scale')

    def cdf(self, value):
        return np.arctan((value - self.loc) / self.scale) / math.pi + 0.5

    def icdf(self, value):
        return self.loc + self.scale * np.tan(math.pi * (value - 0.5))

    @property
    def mean(self):
        return np.full(self._batch_shape(), float('nan'))

    @property
    def variance(self):
        return np.full(self._batch_shape(), float('nan'))

    def entropy(self):
        return np.log(4 * math.pi * self.scale) * np.ones_like(self.loc)
