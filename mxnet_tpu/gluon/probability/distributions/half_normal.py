"""Half-Normal distribution (reference
``python/mxnet/gluon/probability/distributions/half_normal.py``)."""

import math

from .... import numpy as np
from .distribution import Distribution
from .normal import Normal
from .constraint import NonNegative, Positive
from .utils import as_array, erf, erfinv, sample_n_shape_converter

__all__ = ['HalfNormal']


class HalfNormal(Distribution):
    has_grad = True
    support = NonNegative()
    arg_constraints = {'scale': Positive()}

    def __init__(self, scale=1.0, F=None, validate_args=None):
        self.scale = as_array(scale)
        self._base = Normal(0.0, self.scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.scale.shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        return math.log(2) + self._base.log_prob(value)

    def sample(self, size=None):
        return np.abs(self._base.sample(size))

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        new = self._broadcast_args(batch_shape, 'scale')
        new._base = Normal(0.0, new.scale)
        return new

    def cdf(self, value):
        return erf(value / (self.scale * math.sqrt(2)))

    def icdf(self, value):
        return self.scale * math.sqrt(2) * erfinv(value)

    @property
    def mean(self):
        return self.scale * math.sqrt(2 / math.pi)

    @property
    def variance(self):
        return self.scale ** 2 * (1 - 2 / math.pi)

    def entropy(self):
        return (0.5 * np.log(math.pi * self.scale ** 2 / 2) + 0.5)
