"""Relaxed Bernoulli / binary Concrete distribution (reference
``python/mxnet/gluon/probability/distributions/relaxed_bernoulli.py`` —
Maddison et al., "The Concrete Distribution")."""

from .... import numpy as np
from .... import numpy_extension as npx
from .distribution import Distribution
from .constraint import UnitInterval, Real, OpenInterval
from .utils import (as_array, cached_property, prob2logit, logit2prob,
                    sample_n_shape_converter)

__all__ = ['RelaxedBernoulli']


class RelaxedBernoulli(Distribution):
    has_grad = True
    support = OpenInterval(0, 1)
    arg_constraints = {'prob': UnitInterval(), 'logit': Real()}

    def __init__(self, T, prob=None, logit=None, F=None,
                 validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError(
                'Either `prob` or `logit` must be specified, but not both.')
        self.T = as_array(T)
        if prob is not None:
            self.prob = as_array(prob)
        else:
            self.logit = as_array(logit)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    @cached_property
    def prob(self):
        return logit2prob(self.logit, True)

    @cached_property
    def logit(self):
        return prob2logit(self.prob, True)

    def _batch_shape(self):
        p = self.__dict__.get('prob')
        return (p if p is not None else self.logit).shape

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        u = np.clip(np.random.uniform(0.0, 1.0, shape), 1e-7, 1 - 1e-7)
        logistic = np.log(u) - np.log1p(-u)
        return npx.sigmoid((self.logit + logistic) / self.T)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        import copy
        new = copy.copy(self)
        if 'prob' in self.__dict__:
            new.prob = np.broadcast_to(self.prob, batch_shape)
            new.__dict__.pop('logit', None)
        else:
            new.logit = np.broadcast_to(self.logit, batch_shape)
            new.__dict__.pop('prob', None)
        return new

    def log_prob(self, value):
        """BinConcrete density: log λ + log α − (λ+1)(log y + log(1−y))
        − 2 log(α y^{−λ} + (1−y)^{−λ})."""
        if self._validate_args:
            self._validate_samples(value)
        lam, alpha_log = self.T, self.logit
        ly = np.log(value)
        l1y = np.log1p(-value)
        # logsumexp of [alpha_log - lam*ly, -lam*l1y]
        a = alpha_log - lam * ly
        b = -lam * l1y
        m = np.maximum(a, b)
        lse = m + np.log(np.exp(a - m) + np.exp(b - m))
        return (np.log(lam) + alpha_log - (lam + 1) * (ly + l1y)
                - 2 * lse)

    @property
    def mean(self):
        raise NotImplementedError  # no closed form

    @property
    def variance(self):
        raise NotImplementedError
