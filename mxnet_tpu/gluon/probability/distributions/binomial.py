"""Binomial distribution (reference
``python/mxnet/gluon/probability/distributions/binomial.py`` — `n` must
be a non-negative integer scalar)."""

from .... import numpy as np
from .distribution import Distribution
from .constraint import UnitInterval, Real, IntegerInterval
from .utils import (as_array, cached_property, prob2logit, logit2prob,
                    sample_n_shape_converter, gammaln)

__all__ = ['Binomial']


class Binomial(Distribution):
    arg_constraints = {'prob': UnitInterval(), 'logit': Real()}

    def __init__(self, n=1, prob=None, logit=None, F=None,
                 validate_args=None):
        if (n < 0) or (n % 1 != 0):
            raise ValueError(
                'Expect `n` to be non-negative integer, received n={}'
                .format(n))
        if (prob is None) == (logit is None):
            raise ValueError(
                'Either `prob` or `logit` must be specified, but not both.')
        self.n = int(n)
        if prob is not None:
            self.prob = as_array(prob)
        else:
            self.logit = as_array(logit)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    @property
    def support(self):
        return IntegerInterval(0, self.n)

    @cached_property
    def prob(self):
        return logit2prob(self.logit, True)

    @cached_property
    def logit(self):
        return prob2logit(self.prob, True)

    def _batch_shape(self):
        p = self.__dict__.get('prob')
        return (p if p is not None else self.logit).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        coef = (gammaln(np.array(self.n + 1.0)) - gammaln(1 + value)
                - gammaln(self.n - value + 1))
        return (coef + value * np.log(self.prob)
                + (self.n - value) * np.log1p(-self.prob))

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        # sum of n Bernoulli draws in one fused program (n is static)
        p = np.broadcast_to(self.prob, shape)
        trials = np.random.uniform(0.0, 1.0, (self.n,) + tuple(shape))
        return (trials < p).astype('float32').sum(0)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        import copy
        new = copy.copy(self)
        if 'prob' in self.__dict__:
            new.prob = np.broadcast_to(self.prob, batch_shape)
            new.__dict__.pop('logit', None)
        else:
            new.logit = np.broadcast_to(self.logit, batch_shape)
            new.__dict__.pop('prob', None)
        return new

    @property
    def mean(self):
        return self.n * self.prob

    @property
    def variance(self):
        return self.n * self.prob * (1 - self.prob)
