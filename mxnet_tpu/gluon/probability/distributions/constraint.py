"""Constraint zoo for distribution argument/support validation.

Reference: ``python/mxnet/gluon/probability/distributions/constraint.py``
(Real/Interval/Simplex/LowerCholesky/... classes whose ``check`` raises on
violation via the constraint_check op). Same class surface here; checks
run eagerly on host when values are concrete and are skipped under jit
tracing (XLA graphs cannot raise data-dependent errors).
"""

from .... import numpy as np
from .utils import as_array, constraint_check

__all__ = ['Constraint', 'Real', 'Boolean', 'Interval', 'OpenInterval',
           'HalfOpenInterval', 'IntegerInterval', 'IntegerOpenInterval',
           'IntegerHalfOpenInterval', 'GreaterThan', 'GreaterThanEq',
           'LessThan', 'LessThanEq', 'IntegerGreaterThan',
           'IntegerGreaterThanEq', 'IntegerLessThan', 'IntegerLessThanEq',
           'Positive', 'NonNegative', 'PositiveInteger',
           'NonNegativeInteger', 'UnitInterval', 'Simplex',
           'LowerTriangular', 'LowerCholesky', 'PositiveDefinite',
           'Cat', 'Stack', 'dependent', 'dependent_property']


class Constraint:
    """Base class: ``check(value)`` validates and returns the value."""

    def check(self, value):
        raise NotImplementedError

    def __repr__(self):
        return self.__class__.__name__ + '()'


class _Dependent(Constraint):
    """Placeholder for constraints that depend on other arguments."""

    def check(self, value):
        raise ValueError('cannot determine validity of dependent constraint')


class _DependentProperty(property, _Dependent):
    """``@dependent_property`` — a property that is also a (dependent)
    constraint, used for e.g. Uniform.support depending on low/high."""


dependent = _Dependent()
dependent_property = _DependentProperty


def _ok(cond, msg):
    constraint_check(cond, msg)


class Real(Constraint):
    def check(self, value):
        value = as_array(value)
        _ok(value == value, 'value must be a real tensor (got NaN)')
        return value


class Boolean(Constraint):
    def check(self, value):
        value = as_array(value)
        _ok((value == 0) | (value == 1), 'value must be 0 or 1')
        return value


class Interval(Constraint):
    def __init__(self, lower_bound, upper_bound):
        self._low, self._high = lower_bound, upper_bound

    def check(self, value):
        value = as_array(value)
        _ok((value >= self._low) & (value <= self._high),
            f'value must be in [{self._low}, {self._high}]')
        return value

    def __repr__(self):
        return f'{type(self).__name__}({self._low}, {self._high})'


class OpenInterval(Interval):
    def check(self, value):
        value = as_array(value)
        _ok((value > self._low) & (value < self._high),
            f'value must be in ({self._low}, {self._high})')
        return value


class HalfOpenInterval(Interval):
    def check(self, value):
        value = as_array(value)
        _ok((value >= self._low) & (value < self._high),
            f'value must be in [{self._low}, {self._high})')
        return value


def _integral(value):
    return value == np.floor(value)


class IntegerInterval(Interval):
    def check(self, value):
        value = as_array(value)
        _ok(_integral(value) & (value >= self._low) & (value <= self._high),
            f'value must be an integer in [{self._low}, {self._high}]')
        return value


class IntegerOpenInterval(Interval):
    def check(self, value):
        value = as_array(value)
        _ok(_integral(value) & (value > self._low) & (value < self._high),
            f'value must be an integer in ({self._low}, {self._high})')
        return value


class IntegerHalfOpenInterval(Interval):
    def check(self, value):
        value = as_array(value)
        _ok(_integral(value) & (value >= self._low) & (value < self._high),
            f'value must be an integer in [{self._low}, {self._high})')
        return value


class GreaterThan(Constraint):
    def __init__(self, lower_bound):
        self._low = lower_bound

    def check(self, value):
        value = as_array(value)
        _ok(value > self._low, f'value must be > {self._low}')
        return value

    def __repr__(self):
        return f'{type(self).__name__}({self._low})'


class GreaterThanEq(GreaterThan):
    def check(self, value):
        value = as_array(value)
        _ok(value >= self._low, f'value must be >= {self._low}')
        return value


class LessThan(Constraint):
    def __init__(self, upper_bound):
        self._high = upper_bound

    def check(self, value):
        value = as_array(value)
        _ok(value < self._high, f'value must be < {self._high}')
        return value

    def __repr__(self):
        return f'{type(self).__name__}({self._high})'


class LessThanEq(LessThan):
    def check(self, value):
        value = as_array(value)
        _ok(value <= self._high, f'value must be <= {self._high}')
        return value


class IntegerGreaterThan(GreaterThan):
    def check(self, value):
        value = as_array(value)
        _ok(_integral(value) & (value > self._low),
            f'value must be an integer > {self._low}')
        return value


class IntegerGreaterThanEq(GreaterThan):
    def check(self, value):
        value = as_array(value)
        _ok(_integral(value) & (value >= self._low),
            f'value must be an integer >= {self._low}')
        return value


class IntegerLessThan(LessThan):
    def check(self, value):
        value = as_array(value)
        _ok(_integral(value) & (value < self._high),
            f'value must be an integer < {self._high}')
        return value


class IntegerLessThanEq(LessThan):
    def check(self, value):
        value = as_array(value)
        _ok(_integral(value) & (value <= self._high),
            f'value must be an integer <= {self._high}')
        return value


class Positive(GreaterThan):
    def __init__(self):
        super().__init__(0)


class NonNegative(GreaterThanEq):
    def __init__(self):
        super().__init__(0)


class PositiveInteger(IntegerGreaterThan):
    def __init__(self):
        super().__init__(0)


class NonNegativeInteger(IntegerGreaterThanEq):
    def __init__(self):
        super().__init__(0)


class UnitInterval(Interval):
    def __init__(self):
        super().__init__(0, 1)


class Simplex(Constraint):
    def check(self, value):
        value = as_array(value)
        _ok((value >= 0) & (np.abs(value.sum(-1) - 1) < 1e-6),
            'value must lie on the probability simplex')
        return value


class LowerTriangular(Constraint):
    def check(self, value):
        value = as_array(value)
        _ok(np.abs(np.triu(value, 1)).sum((-2, -1)) < 1e-6,
            'value must be lower-triangular')
        return value


class LowerCholesky(Constraint):
    def check(self, value):
        value = as_array(value)
        _ok(np.abs(np.triu(value, 1)).sum((-2, -1)) < 1e-6,
            'value must be lower-triangular')
        _ok(np.diagonal(value, axis1=-2, axis2=-1) > 0,
            'diagonal of a Cholesky factor must be positive')
        return value


class PositiveDefinite(Constraint):
    def check(self, value):
        value = as_array(value)
        # symmetric + positive leading eigenvalue proxy: all eigvals > 0
        _ok(np.abs(value - np.swapaxes(value, -1, -2)).sum((-2, -1))
            < 1e-5, 'value must be symmetric')
        import numpy as _onp
        try:
            w = _onp.linalg.eigvalsh(value.asnumpy())
            _ok(bool((w > 0).all()), 'value must be positive definite')
        except Exception:
            pass  # abstract under trace
        return value


class Cat(Constraint):
    """Apply child constraints to contiguous slices along `axis`
    (reference constraint.Cat)."""

    def __init__(self, constraints, axis=0, lengths=None):
        self._constraints = list(constraints)
        self._axis = axis
        self._lengths = lengths or [1] * len(self._constraints)

    def check(self, value):
        value = as_array(value)
        start = 0
        for c, n in zip(self._constraints, self._lengths):
            idx = [slice(None)] * value.ndim
            idx[self._axis] = slice(start, start + n)
            c.check(value[tuple(idx)])
            start += n
        return value


class Stack(Constraint):
    """Apply child constraints to indexed slices along `axis`
    (reference constraint.Stack)."""

    def __init__(self, constraints, axis=0):
        self._constraints = list(constraints)
        self._axis = axis

    def check(self, value):
        value = as_array(value)
        for i, c in enumerate(self._constraints):
            idx = [slice(None)] * value.ndim
            idx[self._axis] = i
            c.check(value[tuple(idx)])
        return value
