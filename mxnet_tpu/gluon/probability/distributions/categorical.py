"""Categorical distribution (reference
``python/mxnet/gluon/probability/distributions/categorical.py`` —
samples are indices in [0, num_events), float dtype)."""

from .... import numpy as np
from .... import numpy_extension as npx
from .distribution import Distribution
from .constraint import Simplex, Real, IntegerInterval
from .utils import (as_array, cached_property, prob2logit, logit2prob,
                    sample_n_shape_converter, sum_right_most)

__all__ = ['Categorical']


class Categorical(Distribution):
    has_enumerate_support = True
    arg_constraints = {'prob': Simplex(), 'logit': Real()}

    def __init__(self, num_events, prob=None, logit=None, F=None,
                 validate_args=None):
        num_events = int(num_events)
        if num_events < 1:
            raise ValueError('`num_events` should be greater than zero.')
        if (prob is None) == (logit is None):
            raise ValueError(
                'Either `prob` or `logit` must be specified, but not both.')
        self.num_events = num_events
        if prob is not None:
            self.prob = as_array(prob)
        else:
            self.logit = as_array(logit)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    @property
    def support(self):
        return IntegerInterval(0, self.num_events - 1)

    @cached_property
    def prob(self):
        return logit2prob(self.logit, False)

    @cached_property
    def logit(self):
        return prob2logit(self.prob, False)

    def _params(self):
        p = self.__dict__.get('prob')
        return p if p is not None else self.logit

    def _batch_shape(self):
        return self._params().shape[:-1]

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        logp = npx.log_softmax(self.logit, axis=-1)
        idx = npx.one_hot(value.astype('int32'), self.num_events)
        return sum_right_most(logp * idx, 1)

    def sample(self, size=None):
        logits = npx.log_softmax(self.logit, axis=-1)
        if size is None:
            return np.random.categorical(logits).astype('float32')
        size = (size,) if isinstance(size, int) else tuple(size)
        batch = self._batch_shape()
        n = len(batch)
        prefix = size[:len(size) - n] if n else size
        # broadcast batch params then draw one index per position
        tgt = prefix + batch + (self.num_events,)
        logits = np.broadcast_to(logits, tgt)
        return np.random.categorical(logits).astype('float32')

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        import copy
        new = copy.copy(self)
        full = tuple(batch_shape) + (self.num_events,)
        if 'prob' in self.__dict__:
            new.prob = np.broadcast_to(self.prob, full)
            new.__dict__.pop('logit', None)
        else:
            new.logit = np.broadcast_to(self.logit, full)
            new.__dict__.pop('prob', None)
        return new

    def enumerate_support(self):
        batch = self._batch_shape()
        values = np.arange(self.num_events, dtype='float32')
        return values.reshape((self.num_events,) + (1,) * len(batch)) * \
            np.ones((self.num_events,) + batch)

    @property
    def mean(self):
        raise NotImplementedError  # undefined for categorical indices

    @property
    def variance(self):
        raise NotImplementedError

    def entropy(self):
        logp = npx.log_softmax(self.logit, axis=-1)
        return -sum_right_most(np.exp(logp) * logp, 1)
