"""One-hot categorical distribution (reference
``python/mxnet/gluon/probability/distributions/one_hot_categorical.py``)."""

from .... import numpy as np
from .... import numpy_extension as npx
from .categorical import Categorical
from .distribution import Distribution
from .constraint import Simplex, Real
from .utils import sample_n_shape_converter, sum_right_most

__all__ = ['OneHotCategorical']


class OneHotCategorical(Distribution):
    has_enumerate_support = True
    support = Simplex()
    arg_constraints = {'prob': Simplex(), 'logit': Real()}

    def __init__(self, num_events, prob=None, logit=None, F=None,
                 validate_args=None):
        self._categorical = Categorical(num_events, prob, logit)
        self.num_events = self._categorical.num_events
        super().__init__(F=F, event_dim=1, validate_args=validate_args)

    @property
    def prob(self):
        return self._categorical.prob

    @property
    def logit(self):
        return self._categorical.logit

    def _batch_shape(self):
        return self._categorical._batch_shape()

    def log_prob(self, value):
        logp = npx.log_softmax(self.logit, axis=-1)
        return sum_right_most(logp * value, 1)

    def sample(self, size=None):
        idx = self._categorical.sample(size)
        return npx.one_hot(idx.astype('int32'), self.num_events)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        import copy
        new = copy.copy(self)
        new._categorical = self._categorical.broadcast_to(batch_shape)
        return new

    def enumerate_support(self):
        batch = self._batch_shape()
        eye = npx.one_hot(np.arange(self.num_events, dtype='int32'),
                          self.num_events)
        return eye.reshape((self.num_events,) + (1,) * len(batch)
                           + (self.num_events,)) * np.ones(
            (self.num_events,) + batch + (self.num_events,))

    @property
    def mean(self):
        return self.prob

    @property
    def variance(self):
        return self.prob * (1 - self.prob)

    def entropy(self):
        return self._categorical.entropy()
