"""Multinomial distribution (reference
``python/mxnet/gluon/probability/distributions/multinomial.py``)."""

from .... import numpy as np
from .... import numpy_extension as npx
from .distribution import Distribution
from .categorical import Categorical
from .constraint import Simplex, Real, NonNegativeInteger
from .utils import (as_array, sample_n_shape_converter, gammaln,
                    sum_right_most)

__all__ = ['Multinomial']


class Multinomial(Distribution):
    support = NonNegativeInteger()
    arg_constraints = {'prob': Simplex(), 'logit': Real()}

    def __init__(self, num_events, prob=None, logit=None, total_count=1,
                 F=None, validate_args=None):
        if (total_count < 0) or (total_count % 1 != 0):
            raise ValueError(
                'Expect `total_count` to be non-negative integer.')
        self.total_count = int(total_count)
        self._categorical = Categorical(num_events, prob, logit)
        self.num_events = self._categorical.num_events
        super().__init__(F=F, event_dim=1, validate_args=validate_args)

    @property
    def prob(self):
        return self._categorical.prob

    @property
    def logit(self):
        return self._categorical.logit

    def _batch_shape(self):
        return self._categorical._batch_shape()

    def log_prob(self, value):
        logp = npx.log_softmax(self.logit, axis=-1)
        n = value.sum(-1)
        return (gammaln(n + 1) - sum_right_most(gammaln(value + 1), 1)
                + sum_right_most(value * logp, 1))

    def sample(self, size=None):
        # total_count iid categorical draws per output position,
        # scattered to counts; `size` includes the batch shape
        if size is None:
            return self.sample_n(())
        size = (size,) if isinstance(size, int) else tuple(size)
        batch = self._batch_shape()
        prefix = size[:len(size) - len(batch)] if batch else size
        return self.sample_n(prefix)

    def sample_n(self, size=None):
        prefix = sample_n_shape_converter(size)
        idx = self._categorical.sample_n((self.total_count,) + prefix)
        counts = npx.one_hot(idx.astype('int32'), self.num_events)
        return counts.sum(0)

    def broadcast_to(self, batch_shape):
        import copy
        new = copy.copy(self)
        new._categorical = self._categorical.broadcast_to(batch_shape)
        return new

    @property
    def mean(self):
        return self.total_count * self.prob

    @property
    def variance(self):
        return self.total_count * self.prob * (1 - self.prob)
