"""Bernoulli distribution (reference
``python/mxnet/gluon/probability/distributions/bernoulli.py`` — dual
prob/logit parameterization with lazy conversion)."""

from .... import numpy as np
from .... import numpy_extension as npx
from .exp_family import ExponentialFamily
from .constraint import Boolean, UnitInterval, Real
from .utils import (as_array, cached_property, prob2logit, logit2prob,
                    sample_n_shape_converter)

__all__ = ['Bernoulli']


class Bernoulli(ExponentialFamily):
    has_enumerate_support = True
    support = Boolean()
    arg_constraints = {'prob': UnitInterval(), 'logit': Real()}

    def __init__(self, prob=None, logit=None, F=None, validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError(
                'Either `prob` or `logit` must be specified, but not both.')
        if prob is not None:
            self.prob = as_array(prob)
        else:
            self.logit = as_array(logit)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    @cached_property
    def prob(self):
        return logit2prob(self.logit, True)

    @cached_property
    def logit(self):
        return prob2logit(self.prob, True)

    def _batch_shape(self):
        p = self.__dict__.get('prob')
        return (p if p is not None else self.logit).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        logit = self.logit
        # x*logit - softplus(logit), stable in both tails
        return value * logit - npx.softplus(logit)

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        return np.random.bernoulli(self.prob, shape)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        import copy
        new = copy.copy(self)
        if 'prob' in self.__dict__:
            new.prob = np.broadcast_to(self.prob, batch_shape)
            new.__dict__.pop('logit', None)
        else:
            new.logit = np.broadcast_to(self.logit, batch_shape)
            new.__dict__.pop('prob', None)
        return new

    def enumerate_support(self):
        batch = self._batch_shape()
        return np.stack([np.zeros(batch), np.ones(batch)])

    @property
    def mean(self):
        return self.prob

    @property
    def variance(self):
        return self.prob * (1 - self.prob)

    def entropy(self):
        return (npx.softplus(self.logit)
                - self.prob * self.logit)

    @property
    def _natural_params(self):
        return (self.logit,)

    def _log_normalizer(self, x):
        return npx.softplus(x)
