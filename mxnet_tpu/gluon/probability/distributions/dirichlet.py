"""Dirichlet distribution (reference
``python/mxnet/gluon/probability/distributions/dirichlet.py``).
Sampled as normalized reparameterized gammas (pathwise gradients)."""

from .... import numpy as np
from .distribution import Distribution
from .constraint import Positive, Simplex
from .utils import (as_array, sample_n_shape_converter, gammaln, digamma,
                    rgamma, sum_right_most)

__all__ = ['Dirichlet']


class Dirichlet(Distribution):
    has_grad = True
    support = Simplex()
    arg_constraints = {'alpha': Positive()}

    def __init__(self, alpha, F=None, validate_args=None):
        self.alpha = as_array(alpha)
        super().__init__(F=F, event_dim=1, validate_args=validate_args)

    def _batch_shape(self):
        return self.alpha.shape[:-1]

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        a = self.alpha
        return (sum_right_most((a - 1) * np.log(value), 1)
                - sum_right_most(gammaln(a), 1)
                + gammaln(sum_right_most(a, 1)))

    def sample(self, size=None):
        full = (size + self.alpha.shape[-1:]) if size is not None \
            else self.alpha.shape
        g = rgamma(np.broadcast_to(self.alpha, full), full)
        return g / g.sum(-1, keepdims=True)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(
            tuple(batch_shape) + self.alpha.shape[-1:], 'alpha')

    @property
    def mean(self):
        return self.alpha / self.alpha.sum(-1, keepdims=True)

    @property
    def variance(self):
        a0 = self.alpha.sum(-1, keepdims=True)
        return self.alpha * (a0 - self.alpha) / (a0 ** 2 * (a0 + 1))

    def entropy(self):
        a = self.alpha
        k = a.shape[-1]
        a0 = a.sum(-1)
        return (sum_right_most(gammaln(a), 1) - gammaln(a0)
                + (a0 - k) * digamma(a0)
                - sum_right_most((a - 1) * digamma(a), 1))
