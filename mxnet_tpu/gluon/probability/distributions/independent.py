"""Independent — reinterpret batch dims as event dims (reference
``python/mxnet/gluon/probability/distributions/independent.py``)."""

from .distribution import Distribution
from .utils import sum_right_most

__all__ = ['Independent']


class Independent(Distribution):

    def __init__(self, base_distribution, reinterpreted_batch_ndims,
                 validate_args=None):
        self.base_dist = base_distribution
        self.reinterpreted_batch_ndims = reinterpreted_batch_ndims
        event_dim = reinterpreted_batch_ndims + \
            (base_distribution.event_dim or 0)
        super().__init__(F=base_distribution.F, event_dim=event_dim,
                         validate_args=validate_args)

    @property
    def has_grad(self):
        return self.base_dist.has_grad

    @property
    def support(self):
        return self.base_dist.support

    def log_prob(self, value):
        return sum_right_most(self.base_dist.log_prob(value),
                              self.reinterpreted_batch_ndims)

    def sample(self, size=None):
        return self.base_dist.sample(size)

    def sample_n(self, size=None):
        return self.base_dist.sample_n(size)

    @property
    def mean(self):
        return self.base_dist.mean

    @property
    def variance(self):
        return self.base_dist.variance

    def entropy(self):
        return sum_right_most(self.base_dist.entropy(),
                              self.reinterpreted_batch_ndims)
