"""Beta distribution (reference
``python/mxnet/gluon/probability/distributions/beta.py``). Sampled as a
ratio of reparameterized gammas, so pathwise gradients flow to both
concentrations."""

from .... import numpy as np
from .distribution import Distribution
from .constraint import Positive, UnitInterval
from .utils import (as_array, sample_n_shape_converter, gammaln, digamma,
                    rgamma)

__all__ = ['Beta']


def _betaln(a, b):
    return gammaln(a) + gammaln(b) - gammaln(a + b)


class Beta(Distribution):
    has_grad = True
    support = UnitInterval()
    arg_constraints = {'alpha': Positive(), 'beta': Positive()}

    def __init__(self, alpha, beta, F=None, validate_args=None):
        self.alpha = as_array(alpha)
        self.beta = as_array(beta)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return (self.alpha + self.beta).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        a, b = self.alpha, self.beta
        return ((a - 1) * np.log(value) + (b - 1) * np.log1p(-value)
                - _betaln(a, b))

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        ga = rgamma(np.broadcast_to(self.alpha * np.ones_like(self.beta),
                                    shape), shape)
        gb = rgamma(np.broadcast_to(self.beta * np.ones_like(self.alpha),
                                    shape), shape)
        return ga / (ga + gb)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'alpha', 'beta')

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        a, b = self.alpha, self.beta
        return a * b / ((a + b) ** 2 * (a + b + 1))

    def entropy(self):
        a, b = self.alpha, self.beta
        return (_betaln(a, b) - (a - 1) * digamma(a)
                - (b - 1) * digamma(b)
                + (a + b - 2) * digamma(a + b))
