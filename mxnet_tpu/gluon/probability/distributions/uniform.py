"""Uniform distribution (reference
``python/mxnet/gluon/probability/distributions/uniform.py``)."""

from .... import numpy as np
from .distribution import Distribution
from .constraint import Real, dependent_property, Interval
from .utils import as_array, sample_n_shape_converter

__all__ = ['Uniform']


class Uniform(Distribution):
    has_grad = True
    arg_constraints = {'low': Real(), 'high': Real()}

    def __init__(self, low=0.0, high=1.0, F=None, validate_args=None):
        self.low = as_array(low)
        self.high = as_array(high)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    @dependent_property
    def support(self):
        return Interval(self.low, self.high)

    def _batch_shape(self):
        return (self.low + self.high).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        return -np.log(self.high - self.low) * np.ones_like(value)

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        u = np.random.uniform(0.0, 1.0, shape)
        return self.low + (self.high - self.low) * u

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'low', 'high')

    def cdf(self, value):
        return np.clip((value - self.low) / (self.high - self.low), 0, 1)

    def icdf(self, value):
        return self.low + (self.high - self.low) * value

    @property
    def mean(self):
        return (self.low + self.high) / 2

    @property
    def variance(self):
        return (self.high - self.low) ** 2 / 12

    def entropy(self):
        return np.log(self.high - self.low)
