"""Pareto distribution (reference
``python/mxnet/gluon/probability/distributions/pareto.py``)."""

from .... import numpy as np
from .distribution import Distribution
from .constraint import Positive, dependent_property, GreaterThanEq
from .utils import as_array, sample_n_shape_converter

__all__ = ['Pareto']


class Pareto(Distribution):
    has_grad = True
    arg_constraints = {'alpha': Positive(), 'scale': Positive()}

    def __init__(self, alpha, scale=1.0, F=None, validate_args=None):
        self.alpha = as_array(alpha)
        self.scale = as_array(scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    @dependent_property
    def support(self):
        return GreaterThanEq(self.scale)

    def _batch_shape(self):
        return (self.alpha + self.scale).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        return (np.log(self.alpha) + self.alpha * np.log(self.scale)
                - (self.alpha + 1) * np.log(value))

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        u = np.random.uniform(0.0, 1.0, shape)
        return self.scale * (1 - u) ** (-1 / self.alpha)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'alpha', 'scale')

    def cdf(self, value):
        return 1 - (self.scale / value) ** self.alpha

    def icdf(self, value):
        return self.scale * (1 - value) ** (-1 / self.alpha)

    @property
    def mean(self):
        m = self.alpha * self.scale / (self.alpha - 1)
        return np.where(self.alpha > 1, m,
                        np.full(m.shape, float('inf')))

    @property
    def variance(self):
        a = self.alpha
        v = self.scale ** 2 * a / ((a - 1) ** 2 * (a - 2))
        return np.where(a > 2, v, np.full(v.shape, float('inf')))

    def entropy(self):
        return np.log(self.scale / self.alpha) + 1 + 1 / self.alpha
