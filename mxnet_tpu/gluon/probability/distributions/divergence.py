"""KL divergence registry (reference
``python/mxnet/gluon/probability/distributions/divergence.py`` —
``register_kl(P, Q)`` decorator + name-based dispatch + ``empirical_kl``
Monte-Carlo fallback). All closed forms below are standard results; each
is a pure NDArray program, differentiable end-to-end (the ELBO use case)."""

import math

from .... import numpy as np
from .... import numpy_extension as npx
from .utils import gammaln, digamma, sum_right_most, EULER

from .normal import Normal
from .bernoulli import Bernoulli
from .categorical import Categorical
from .one_hot_categorical import OneHotCategorical
from .uniform import Uniform
from .cauchy import Cauchy
from .laplace import Laplace
from .poisson import Poisson
from .geometric import Geometric
from .exponential import Exponential
from .pareto import Pareto
from .gumbel import Gumbel
from .gamma import Gamma
from .beta import Beta
from .dirichlet import Dirichlet
from .half_normal import HalfNormal
from .binomial import Binomial
from .multivariate_normal import MultivariateNormal

__all__ = ['register_kl', 'kl_divergence', 'empirical_kl']

_KL_REGISTRY = {}


def empirical_kl(p, q, n_samples=1):
    """Monte-Carlo KL(p||q) = E_p[log p(x) − log q(x)] — works for any
    pair with log_prob + sampling (reference empirical_kl)."""
    samples = p.sample_n((n_samples,))
    return (p.log_prob(samples) - q.log_prob(samples)).mean(0)


def register_kl(typeP, typeQ):
    """Decorator registering KL(P||Q) (reference register_kl)."""

    def deco(func):
        _KL_REGISTRY[(typeP.__name__, typeQ.__name__)] = func
        return func

    return deco


def kl_divergence(p, q):
    r"""KL(p||q), dispatched on the pair of distribution types."""
    func = _dispatch_kl(p.__class__.__name__, q.__class__.__name__)
    return func(p, q)


def _dispatch_kl(type_p, type_q):
    func = _KL_REGISTRY.get((type_p, type_q))
    if func is None:
        raise NotImplementedError(
            'KL divergence between {} and {} is not implemented.'
            .format(type_p, type_q))
    return func


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - np.log(var_ratio))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    # xlogy-safe: the p=0 / p=1 limits contribute 0, not 0*(-inf)=nan
    pp, qp = p.prob, q.prob
    t1 = np.where(pp > 0, pp * (np.log(np.maximum(pp, 1e-38))
                                - np.log(qp)), np.zeros_like(pp))
    t0 = np.where(pp < 1, (1 - pp) * (np.log1p(-np.minimum(pp, 1 - 1e-7))
                                      - np.log1p(-qp)),
                  np.zeros_like(pp))
    return t1 + t0


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    lp = npx.log_softmax(p.logit, axis=-1)
    lq = npx.log_softmax(q.logit, axis=-1)
    return sum_right_most(np.exp(lp) * (lp - lq), 1)


@register_kl(OneHotCategorical, OneHotCategorical)
def _kl_onehotcategorical_onehotcategorical(p, q):
    return _kl_categorical_categorical(p._categorical, q._categorical)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    # finite iff q's support contains p's
    result = np.log((q.high - q.low) / (p.high - p.low))
    return np.where((q.low <= p.low) & (q.high >= p.high), result,
                    np.full(result.shape, float('inf')))


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    # closed form (Chyzak & Nielsen 2019)
    t1 = np.log((p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2)
    t2 = np.log(4 * p.scale * q.scale)
    return t1 - t2


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    scale_ratio = p.scale / q.scale
    loc_diff = np.abs(p.loc - q.loc) / q.scale
    return (-np.log(scale_ratio) - 1 + loc_diff
            + scale_ratio * np.exp(-loc_diff / scale_ratio))


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    return p.rate * (np.log(p.rate) - np.log(q.rate)) - (p.rate - q.rate)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    return (-p.entropy() - np.log(q.prob)
            - (1 - p.prob) / p.prob * np.log1p(-q.prob))


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    # KL = log(sq/sp) + sp/sq - 1 (rates lambda = 1/scale)
    scale_ratio = p.scale / q.scale
    return scale_ratio - 1 - np.log(scale_ratio)


@register_kl(Pareto, Pareto)
def _kl_pareto_pareto(p, q):
    scale_ratio = p.scale / q.scale
    alpha_ratio = q.alpha / p.alpha
    t1 = q.alpha * np.log(scale_ratio)
    t2 = -np.log(alpha_ratio)
    result = t1 + t2 + alpha_ratio - 1
    return np.where(p.scale >= q.scale, result,
                    np.full(result.shape, float('inf')))


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    # log(b2/b1) + γ(b1/b2 − 1) + (μ1−μ2)/b2
    #   + exp((μ2−μ1)/b2 + lgamma(1 + b1/b2)) − 1
    beta_ratio = p.scale / q.scale
    loc_diff = (p.loc - q.loc) / q.scale
    return (-np.log(beta_ratio) + EULER * (beta_ratio - 1) + loc_diff
            + np.exp(-loc_diff + gammaln(1 + beta_ratio)) - 1)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    # (shape a, scale s) parameterization
    ap, bp = p.shape, 1 / p.scale
    aq, bq = q.shape, 1 / q.scale
    return ((ap - aq) * digamma(ap) - gammaln(ap) + gammaln(aq)
            + aq * (np.log(bp) - np.log(bq))
            + ap * (bq / bp - 1))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def betaln(a, b):
        return gammaln(a) + gammaln(b) - gammaln(a + b)

    sp = p.alpha + p.beta
    return (betaln(q.alpha, q.beta) - betaln(p.alpha, p.beta)
            + (p.alpha - q.alpha) * digamma(p.alpha)
            + (p.beta - q.beta) * digamma(p.beta)
            + (q.alpha - p.alpha + q.beta - p.beta) * digamma(sp))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    a0 = p.alpha.sum(-1)
    return (gammaln(a0) - sum_right_most(gammaln(p.alpha), 1)
            - gammaln(q.alpha.sum(-1))
            + sum_right_most(gammaln(q.alpha), 1)
            + sum_right_most(
                (p.alpha - q.alpha)
                * (digamma(p.alpha) - digamma(a0)[..., None]), 1))


@register_kl(HalfNormal, HalfNormal)
def _kl_halfNormal_halfNormal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    return 0.5 * (var_ratio - 1 - np.log(var_ratio))


@register_kl(Binomial, Binomial)
def _kl_binomial_binomial(p, q):
    if p.n != q.n:
        raise ValueError('KL between binomials with different trial '
                         'counts is not implemented')
    return p.n * (p.prob * (np.log(p.prob) - np.log(q.prob))
                  + (1 - p.prob) * (np.log1p(-p.prob)
                                    - np.log1p(-q.prob)))


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    k = p.loc.shape[-1]
    half_p = p._half_log_det()
    half_q = q._half_log_det()
    qinv = q.precision
    diff = q.loc - p.loc
    tr = np.einsum('...ij,...ji->...', qinv, p.cov)
    maha = np.einsum('...i,...ij,...j->...', diff, qinv, diff)
    return half_q - half_p + 0.5 * (tr + maha - k)


@register_kl(Uniform, Normal)
def _kl_uniform_normal(p, q):
    # -H(p) + E_p[-log q]
    width = p.high - p.low
    e2 = (p.high ** 3 - p.low ** 3) / (3 * width)  # E[x^2]
    mean = (p.high + p.low) / 2
    cross = (0.5 * math.log(2 * math.pi) + np.log(q.scale)
             + (e2 - 2 * mean * q.loc + q.loc ** 2)
             / (2 * q.scale ** 2))
    return -np.log(width) + cross


@register_kl(Uniform, Gumbel)
def _kl_uniform_gumbel(p, q):
    # E_p[-log q] with q Gumbel(mu, beta): log beta + E[z] + E[e^{-z}]
    width = p.high - p.low
    zl = (p.low - q.loc) / q.scale
    zh = (p.high - q.loc) / q.scale
    mean_z = (zl + zh) / 2
    e_exp = (np.exp(-zl) - np.exp(-zh)) * q.scale / width
    return (-np.log(width) + np.log(q.scale) + mean_z + e_exp)


@register_kl(Exponential, Gumbel)
def _kl_exponential_gumbel(p, q):
    # p Exp(scale s); q Gumbel(mu, b). E[x] = s.
    s, mu, b = p.scale, q.loc, q.scale
    t1 = -np.log(s) - 1                        # -H(p) = -(1+log s)
    t2 = np.log(b) + (s - mu * np.ones_like(s)) / b
    # E[e^{-(x-mu)/b}] = e^{mu/b} * (1/(1+s/b))
    t3 = np.exp(mu / b) / (1 + s / b)
    return t1 + t2 + t3


@register_kl(Exponential, Normal)
def _kl_exponential_normal(p, q):
    # E_p[x]=s, E_p[x^2]=2s^2
    s = p.scale
    var = q.scale ** 2
    return (-np.log(s) - 1
            + 0.5 * math.log(2 * math.pi) + np.log(q.scale)
            + (2 * s ** 2 - 2 * q.loc * s + q.loc ** 2) / (2 * var))


@register_kl(Exponential, Gamma)
def _kl_exponential_gamma(p, q):
    # p = Gamma(1, s): E_p[log x] = log s − γ, H(p) = 1 + log s
    s = p.scale
    aq, sq = q.shape, q.scale
    return (-np.log(s) - 1 + gammaln(aq) + aq * np.log(sq)
            - (aq - 1) * (np.log(s) - EULER) + s / sq)
