"""Shared helpers for the probability package.

Reference surface: ``python/mxnet/gluon/probability/distributions/utils.py``
(prob2logit/logit2prob/getF/sample_n_shape_converter/cached_property and the
special-function aliases). TPU-native notes: there is one array namespace
(``mx.np`` over jax), so ``getF`` is a compatibility no-op; special
functions come from the op registry (XLA kernels); reparameterized gamma
sampling is registered here as a *differentiable* stochastic op —
``jax.random.gamma`` carries implicit-reparameterization gradients
(Figurnov et al.), which the tape records like any other VJP. That single
op gives pathwise gradients to Gamma/Beta/Dirichlet/Chi2/F/StudentT.
"""

import math

from .... import numpy as np
from .... import numpy_extension as npx
from ....ops.registry import register, invoke, get_op
from ....ndarray.ndarray import NDArray

__all__ = ['getF', 'prob2logit', 'logit2prob', 'cached_property',
           'constraint_check', 'sample_n_shape_converter', 'gammaln',
           'digamma', 'erf', 'erfinv', 'as_array', 'sum_right_most',
           'rgamma', 'EULER']

EULER = 0.57721566490153286  # Euler–Mascheroni

gammaln = np.gammaln
digamma = np.digamma
erf = np.erf
erfinv = np.erfinv


def getF(*params):
    """Single-namespace build: always ``mx.np`` (kept for API parity with
    the reference's ndarray/symbol mode switch)."""
    return np


def as_array(x, dtype='float32'):
    if isinstance(x, NDArray):
        return x
    return np.array(x, dtype=dtype)


def sum_right_most(value, ndim):
    """Sum out the rightmost `ndim` dimensions (event reduction)."""
    if ndim == 0:
        return value
    return value.reshape(value.shape[:-ndim] + (-1,)).sum(-1) \
        if ndim > 1 else value.sum(-1)


def prob2logit(prob, binary=True):
    """Probabilities → logits; binary uses the sigmoid inverse, multiclass
    the (normalized) log (reference utils.prob2logit)."""
    prob = as_array(prob)
    eps = 1e-7
    prob = np.clip(prob, eps, 1.0 - eps)
    if binary:
        return np.log(prob) - np.log1p(-prob)
    return np.log(prob)


def logit2prob(logit, binary=True):
    logit = as_array(logit)
    if binary:
        return npx.sigmoid(logit)
    return npx.softmax(logit, axis=-1)


class cached_property:
    """Compute once per instance (reference utils.cached_property)."""

    def __init__(self, func):
        self._func = func
        self.__doc__ = getattr(func, '__doc__', None)
        self._name = func.__name__

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        val = self._func(obj)
        obj.__dict__[self._name] = val
        return val


def constraint_check(condition, err_msg='constraint violated'):
    """Eager-mode validation: raises when `condition` is concretely false;
    a no-op under tracing (jit graphs cannot branch on data — the
    reference's constraint_check op becomes a device-side nan instead).
    Returns 1.0 so callers can multiply it in, like the reference op."""
    if isinstance(condition, NDArray):
        try:
            ok = bool(condition.asnumpy().all())
        except Exception:
            return 1.0  # abstract under trace: skip host check
        if not ok:
            raise ValueError(err_msg)
    elif not condition:
        raise ValueError(err_msg)
    return 1.0


def sample_n_shape_converter(size):
    """Normalize `sample_n` size to a tuple prefix."""
    if size is None:
        return ()
    if isinstance(size, (int,)):
        return (size,)
    return tuple(size)


@register('_prob_gamma_rsample', stochastic=True, differentiable=True,
          namespaces=())
def _prob_gamma_rsample(alpha, size=None, key=None):
    """Reparameterized standard-gamma sample (scale folded in by the
    caller so its gradient is pure NDArray math)."""
    import jax
    import jax.numpy as jnp
    shape = tuple(size) if size is not None else jnp.shape(alpha)
    return jax.random.gamma(key, alpha, shape, dtype=jnp.float32)


def rgamma(alpha, size=None):
    """Differentiable Gamma(alpha, 1) sample as an NDArray."""
    alpha = as_array(alpha)
    return invoke('_prob_gamma_rsample', (alpha,),
                  {'size': tuple(size) if size is not None else None})
