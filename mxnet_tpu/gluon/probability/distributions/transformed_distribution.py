"""TransformedDistribution (reference
``python/mxnet/gluon/probability/distributions/transformed_distribution.py``
— push a base distribution through a chain of invertible transforms;
log_prob walks the chain backwards accumulating log-det-Jacobians)."""

from .distribution import Distribution
from ..transformation.transformation import Transformation
from .utils import sum_right_most

__all__ = ['TransformedDistribution']


class TransformedDistribution(Distribution):

    def __init__(self, base_dist, transforms, validate_args=None):
        self._base_dist = base_dist
        if isinstance(transforms, Transformation):
            transforms = [transforms]
        self._transforms = list(transforms)
        event_dim = max([base_dist.event_dim or 0] +
                        [t.event_dim for t in self._transforms])
        super().__init__(F=base_dist.F, event_dim=event_dim,
                         validate_args=validate_args)

    @property
    def has_grad(self):
        return self._base_dist.has_grad

    def sample(self, size=None):
        x = self._base_dist.sample(size)
        for t in self._transforms:
            x = t(x)
        return x

    def sample_n(self, size=None):
        x = self._base_dist.sample_n(size)
        for t in self._transforms:
            x = t(x)
        return x

    def log_prob(self, value):
        log_prob = 0.0
        y = value
        event_dim = self.event_dim
        for t in reversed(self._transforms):
            x = t.inv(y)
            term = t.log_det_jacobian(x, y)
            log_prob = log_prob - sum_right_most(
                term, event_dim - t.event_dim)
            y = x
        base_dim = self._base_dist.event_dim or 0
        log_prob = log_prob + sum_right_most(
            self._base_dist.log_prob(y), event_dim - base_dim)
        return log_prob

    def cdf(self, value):
        y = value
        sign = 1
        for t in reversed(self._transforms):
            y = t.inv(y)
            sign = sign * t.sign
        base_cdf = self._base_dist.cdf(y)
        if isinstance(sign, int) and sign == 1:
            return base_cdf
        return sign * (base_cdf - 0.5) + 0.5

    def icdf(self, value):
        sign = 1
        for t in self._transforms:
            sign = sign * t.sign
        if not (isinstance(sign, int) and sign == 1):
            value = sign * (value - 0.5) + 0.5
        x = self._base_dist.icdf(value)
        for t in self._transforms:
            x = t(x)
        return x
