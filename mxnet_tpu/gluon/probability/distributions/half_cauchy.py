"""Half-Cauchy distribution (reference
``python/mxnet/gluon/probability/distributions/half_cauchy.py`` — the
reference builds it as TransformedDistribution(Cauchy, AbsTransform);
here closed forms are used directly, same API)."""

import math

from .... import numpy as np
from .distribution import Distribution
from .cauchy import Cauchy
from .constraint import NonNegative, Positive
from .utils import as_array, sample_n_shape_converter

__all__ = ['HalfCauchy']


class HalfCauchy(Distribution):
    has_grad = True
    support = NonNegative()
    arg_constraints = {'scale': Positive()}

    def __init__(self, scale=1.0, F=None, validate_args=None):
        self.scale = as_array(scale)
        self._base = Cauchy(0.0, self.scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.scale.shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        return math.log(2) + self._base.log_prob(value)

    def sample(self, size=None):
        return np.abs(self._base.sample(size))

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        new = self._broadcast_args(batch_shape, 'scale')
        new._base = Cauchy(0.0, new.scale)
        return new

    def cdf(self, value):
        return 2 * np.arctan(value / self.scale) / math.pi

    def icdf(self, value):
        return self.scale * np.tan(math.pi * value / 2)

    @property
    def mean(self):
        return np.full(self._batch_shape(), float('nan'))

    @property
    def variance(self):
        return np.full(self._batch_shape(), float('nan'))

    def entropy(self):
        return np.log(2 * math.pi * self.scale)
