"""Geometric distribution (reference
``python/mxnet/gluon/probability/distributions/geometric.py`` — number
of failures before the first success)."""

from .... import numpy as np
from .distribution import Distribution
from .constraint import UnitInterval, Real, NonNegativeInteger
from .utils import (as_array, cached_property, prob2logit, logit2prob,
                    sample_n_shape_converter)

__all__ = ['Geometric']


class Geometric(Distribution):
    support = NonNegativeInteger()
    arg_constraints = {'prob': UnitInterval(), 'logit': Real()}

    def __init__(self, prob=None, logit=None, F=None, validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError(
                'Either `prob` or `logit` must be specified, but not both.')
        if prob is not None:
            self.prob = as_array(prob)
        else:
            self.logit = as_array(logit)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    @cached_property
    def prob(self):
        return logit2prob(self.logit, True)

    @cached_property
    def logit(self):
        return prob2logit(self.prob, True)

    def _batch_shape(self):
        p = self.__dict__.get('prob')
        return (p if p is not None else self.logit).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        return value * np.log1p(-self.prob) + np.log(self.prob)

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        u = np.clip(np.random.uniform(0.0, 1.0, shape), 1e-7, 1 - 1e-7)
        return np.floor(np.log(u) / np.log1p(-self.prob))

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        import copy
        new = copy.copy(self)
        if 'prob' in self.__dict__:
            new.prob = np.broadcast_to(self.prob, batch_shape)
            new.__dict__.pop('logit', None)
        else:
            new.logit = np.broadcast_to(self.logit, batch_shape)
            new.__dict__.pop('prob', None)
        return new

    @property
    def mean(self):
        return (1 - self.prob) / self.prob

    @property
    def variance(self):
        return (1 - self.prob) / self.prob ** 2

    def entropy(self):
        p = self.prob
        return -((1 - p) * np.log1p(-p) + p * np.log(p)) / p
