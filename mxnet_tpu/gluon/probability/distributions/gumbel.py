"""Gumbel distribution (reference
``python/mxnet/gluon/probability/distributions/gumbel.py``)."""

import math

from .... import numpy as np
from .distribution import Distribution
from .constraint import Real, Positive
from .utils import as_array, sample_n_shape_converter, EULER

__all__ = ['Gumbel']


class Gumbel(Distribution):
    has_grad = True
    support = Real()
    arg_constraints = {'loc': Real(), 'scale': Positive()}

    def __init__(self, loc, scale=1, F=None, validate_args=None):
        self.loc = as_array(loc)
        self.scale = as_array(scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return (self.loc + self.scale).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        z = (value - self.loc) / self.scale
        return -(z + np.exp(-z)) - np.log(self.scale)

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        u = np.clip(np.random.uniform(0.0, 1.0, shape), 1e-7, 1 - 1e-7)
        return self.loc - self.scale * np.log(-np.log(u))

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'loc', 'scale')

    def cdf(self, value):
        return np.exp(-np.exp(-(value - self.loc) / self.scale))

    def icdf(self, value):
        return self.loc - self.scale * np.log(-np.log(value))

    @property
    def mean(self):
        return self.loc + self.scale * EULER

    @property
    def variance(self):
        return (math.pi ** 2 / 6) * self.scale ** 2

    @property
    def stddev(self):
        return math.pi / math.sqrt(6) * self.scale

    def entropy(self):
        return np.log(self.scale) + 1 + EULER
