"""Distribution classes (reference
``python/mxnet/gluon/probability/distributions/__init__.py``)."""

from .distribution import *
from .exp_family import *
from .exponential import *
from .weibull import *
from .pareto import *
from .uniform import *
from .normal import *
from .laplace import *
from .cauchy import *
from .half_cauchy import *
from .poisson import *
from .geometric import *
from .negative_binomial import *
from .gamma import *
from .dirichlet import *
from .beta import *
from .chi2 import *
from .fishersnedecor import *
from .studentT import *
from .half_normal import *
from .independent import *
from .bernoulli import *
from .binomial import *
from .relaxed_bernoulli import *
from .gumbel import *
from .categorical import *
from .one_hot_categorical import *
from .relaxed_one_hot_categorical import *
from .multinomial import *
from .multivariate_normal import *
from .transformed_distribution import *
from .divergence import *
from .utils import getF, prob2logit, logit2prob
from . import constraint
