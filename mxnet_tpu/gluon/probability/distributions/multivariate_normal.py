"""Multivariate normal distribution (reference
``python/mxnet/gluon/probability/distributions/multivariate_normal.py``
— exactly one of cov / precision / scale_tril given). All three
parameterizations are normalized to the Cholesky factor once; log_prob
and sampling are einsum programs that XLA maps onto the MXU."""

import math

from .... import numpy as np
from .distribution import Distribution
from .constraint import Real, PositiveDefinite, LowerCholesky
from .utils import as_array, cached_property, sample_n_shape_converter

__all__ = ['MultivariateNormal']


class MultivariateNormal(Distribution):
    has_grad = True
    support = Real()
    arg_constraints = {'loc': Real(), 'cov': PositiveDefinite(),
                       'precision': PositiveDefinite(),
                       'scale_tril': LowerCholesky()}

    def __init__(self, loc, cov=None, precision=None, scale_tril=None,
                 F=None, validate_args=None):
        if (cov is not None) + (precision is not None) + \
                (scale_tril is not None) != 1:
            raise ValueError('Exactly one of `cov` or `precision` or '
                             '`scale_tril` may be specified.')
        self.loc = as_array(loc)
        if cov is not None:
            self.cov = as_array(cov)
        elif precision is not None:
            self.precision = as_array(precision)
        else:
            self.scale_tril = as_array(scale_tril)
        super().__init__(F=F, event_dim=1, validate_args=validate_args)

    # lazy conversions between the three parameterizations
    @cached_property
    def scale_tril(self):
        if 'cov' in self.__dict__:
            return np.linalg.cholesky(self.cov)
        # precision given: L_prec = chol(P); scale_tril = inv(L_prec)^T
        lp = np.linalg.cholesky(self.precision)
        eye = np.broadcast_to(np.eye(lp.shape[-1]), lp.shape)
        return np.swapaxes(np.linalg.trsm(lp, eye), -1, -2)

    @cached_property
    def cov(self):
        L = self.scale_tril
        return np.einsum('...ik,...jk->...ij', L, L)

    @cached_property
    def precision(self):
        return np.linalg.inv(self.cov)

    def _batch_shape(self):
        import numpy as _onp
        return _onp.broadcast_shapes(self.loc.shape[:-1],
                                     self.scale_tril.shape[:-2])

    def _half_log_det(self):
        return np.log(np.diagonal(self.scale_tril, axis1=-2,
                                  axis2=-1)).sum(-1)

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        k = self.loc.shape[-1]
        diff = value - self.loc
        # triangular solve L z = diff (no explicit inverse): the
        # registered la_op trsm kernel, batched over leading dims
        L = np.broadcast_to(
            self.scale_tril, diff.shape[:-1] + self.scale_tril.shape[-2:])
        z = np.linalg.trsm(L, diff[..., None])[..., 0]
        maha = (z ** 2).sum(-1)
        return (-0.5 * (k * math.log(2 * math.pi) + maha)
                - self._half_log_det())

    def sample(self, size=None):
        batch = size if size is not None else self._batch_shape()
        shape = tuple(batch) + self.loc.shape[-1:]
        eps = np.random.normal(0.0, 1.0, shape)
        return self.loc + np.einsum('...ij,...j->...i', self.scale_tril,
                                    eps)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        import copy
        new = copy.copy(self)
        k = self.loc.shape[-1]
        new.loc = np.broadcast_to(self.loc, tuple(batch_shape) + (k,))
        return new

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return np.diagonal(self.cov, axis1=-2, axis2=-1) * \
            np.ones_like(self.loc)

    def entropy(self):
        k = self.loc.shape[-1]
        return (0.5 * k * (1 + math.log(2 * math.pi))
                + self._half_log_det())
