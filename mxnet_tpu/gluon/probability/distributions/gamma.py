"""Gamma distribution (reference
``python/mxnet/gluon/probability/distributions/gamma.py`` — (shape,
scale) parameterization). Sampling is pathwise-differentiable via the
implicit-reparameterized gamma op (utils.rgamma)."""

from .... import numpy as np
from .distribution import Distribution
from .constraint import Positive
from .utils import (as_array, sample_n_shape_converter, gammaln, digamma,
                    rgamma)

__all__ = ['Gamma']


class Gamma(Distribution):
    has_grad = True
    support = Positive()
    arg_constraints = {'shape': Positive(), 'scale': Positive()}

    def __init__(self, shape, scale=1.0, F=None, validate_args=None):
        self.shape = as_array(shape)
        self.scale = as_array(scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return (self.shape + self.scale).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        a, s = self.shape, self.scale
        return ((a - 1) * np.log(value) - value / s - gammaln(a)
                - a * np.log(s))

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        alpha = np.broadcast_to(self.shape * np.ones_like(self.scale),
                                shape)
        return rgamma(alpha, shape) * self.scale

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'shape', 'scale')

    @property
    def mean(self):
        return self.shape * self.scale

    @property
    def variance(self):
        return self.shape * self.scale ** 2

    def entropy(self):
        a = self.shape * np.ones_like(self.scale)
        return (a + np.log(self.scale * np.ones_like(a)) + gammaln(a)
                + (1 - a) * digamma(a))
