"""Laplace distribution (reference
``python/mxnet/gluon/probability/distributions/laplace.py``)."""

import math

from .... import numpy as np
from .distribution import Distribution
from .constraint import Real, Positive
from .utils import as_array, sample_n_shape_converter

__all__ = ['Laplace']


class Laplace(Distribution):
    has_grad = True
    support = Real()
    arg_constraints = {'loc': Real(), 'scale': Positive()}

    def __init__(self, loc=0.0, scale=1.0, F=None, validate_args=None):
        self.loc = as_array(loc)
        self.scale = as_array(scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return (self.loc + self.scale).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        return (-np.abs(value - self.loc) / self.scale
                - np.log(2 * self.scale))

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        # inverse-CDF from U(-1/2, 1/2): loc - b*sign(u)*log1p(-2|u|)
        u = np.random.uniform(-0.5, 0.5, shape)
        return self.loc - self.scale * np.sign(u) * np.log1p(
            -2 * np.abs(u))

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'loc', 'scale')

    def cdf(self, value):
        z = (value - self.loc) / self.scale
        return 0.5 - 0.5 * np.sign(z) * np.expm1(-np.abs(z))

    def icdf(self, value):
        u = value - 0.5
        return self.loc - self.scale * np.sign(u) * np.log1p(
            -2 * np.abs(u))

    @property
    def mean(self):
        return self.loc * np.ones_like(self.scale)

    @property
    def variance(self):
        return 2 * (self.scale ** 2) * np.ones_like(self.loc)

    def entropy(self):
        return 1 + np.log(2 * self.scale) * np.ones_like(self.loc)
