"""Negative binomial distribution (reference
``python/mxnet/gluon/probability/distributions/negative_binomial.py`` —
number of failures before the n-th success; ``prob`` is the success
probability, matching scipy.stats.nbinom)."""

from .... import numpy as np
from .distribution import Distribution
from .constraint import (UnitInterval, Real, NonNegativeInteger,
                         PositiveInteger)
from .utils import (as_array, cached_property, prob2logit, logit2prob,
                    sample_n_shape_converter, gammaln)

__all__ = ['NegativeBinomial']


class NegativeBinomial(Distribution):
    support = NonNegativeInteger()
    arg_constraints = {'n': PositiveInteger(), 'prob': UnitInterval(),
                       'logit': Real()}

    def __init__(self, n, prob=None, logit=None, F=None,
                 validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError(
                'Either `prob` or `logit` must be specified, but not both.')
        self.n = as_array(n)
        if prob is not None:
            self.prob = as_array(prob)
        else:
            self.logit = as_array(logit)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    @cached_property
    def prob(self):
        return logit2prob(self.logit, True)

    @cached_property
    def logit(self):
        return prob2logit(self.prob, True)

    def _batch_shape(self):
        return (self.n + self.prob).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        coef = (gammaln(value + self.n) - gammaln(1 + value)
                - gammaln(self.n))
        return (coef + self.n * np.log(self.prob)
                + value * np.log1p(-self.prob))

    def sample(self, size=None):
        # gamma–Poisson mixture (the reference op's sampling path,
        # src/operator/random/sample_op.cc negative_binomial)
        shape = size if size is not None else self._batch_shape()
        lam = np.random.gamma(
            np.broadcast_to(self.n * np.ones_like(self.prob), shape),
            (1 - self.prob) / self.prob, shape)
        return np.random.poisson(lam, shape).astype('float32')

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        import copy
        new = copy.copy(self)
        new.n = np.broadcast_to(self.n, batch_shape)
        if 'prob' in self.__dict__:
            new.prob = np.broadcast_to(self.prob, batch_shape)
            new.__dict__.pop('logit', None)
        else:
            new.logit = np.broadcast_to(self.logit, batch_shape)
            new.__dict__.pop('prob', None)
        return new

    @property
    def mean(self):
        return self.n * (1 - self.prob) / self.prob

    @property
    def variance(self):
        return self.n * (1 - self.prob) / self.prob ** 2
