"""Exponential-family base.

Reference: ``python/mxnet/gluon/probability/distributions/exp_family.py``
— defines the natural-parameter API (``_natural_params``,
``_log_normalizer``, ``_mean_carrier_measure``) and derives ``entropy``
via the Bregman divergence of the log-normalizer using autograd.

Here members override ``entropy`` with closed forms (cheaper and exact —
no autograd round-trip inside a metric), and the natural-parameter hooks
remain for subclasses that expose them (Normal does).
"""

from .distribution import Distribution

__all__ = ['ExponentialFamily']


class ExponentialFamily(Distribution):
    r"""p(x|θ) = h(x) exp(<η(θ), t(x)> − A(η))."""

    @property
    def _natural_params(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError
