"""Chi-squared distribution (reference
``python/mxnet/gluon/probability/distributions/chi2.py`` —
Chi2(df) = Gamma(df/2, 2))."""

from .gamma import Gamma
from .constraint import Positive
from .utils import as_array

__all__ = ['Chi2']


class Chi2(Gamma):
    arg_constraints = {'df': Positive()}

    def __init__(self, df, F=None, validate_args=None):
        df = as_array(df)
        super().__init__(df / 2, 2.0, F, validate_args)

    @property
    def df(self):
        return self.shape * 2
