"""Fisher–Snedecor (F) distribution (reference
``python/mxnet/gluon/probability/distributions/fishersnedecor.py``).
Sampled as a ratio of reparameterized chi-squareds."""

from .... import numpy as np
from .distribution import Distribution
from .constraint import Positive
from .utils import as_array, sample_n_shape_converter, gammaln, rgamma

__all__ = ['FisherSnedecor']


class FisherSnedecor(Distribution):
    has_grad = True
    support = Positive()
    arg_constraints = {'df1': Positive(), 'df2': Positive()}

    def __init__(self, df1, df2, F=None, validate_args=None):
        self.df1 = as_array(df1)
        self.df2 = as_array(df2)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return (self.df1 + self.df2).shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        d1, d2 = self.df1, self.df2
        betaln = (gammaln(d1 / 2) + gammaln(d2 / 2)
                  - gammaln((d1 + d2) / 2))
        return (0.5 * (d1 * np.log(d1) + d1 * np.log(value)
                       + d2 * np.log(d2)
                       - (d1 + d2) * np.log(d1 * value + d2))
                - np.log(value) - betaln)

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        ones = np.ones(shape) if shape else np.array(1.0)
        d1 = np.broadcast_to(self.df1 * ones, shape)
        d2 = np.broadcast_to(self.df2 * ones, shape)
        x1 = rgamma(d1 / 2, shape) * 2 / d1
        x2 = rgamma(d2 / 2, shape) * 2 / d2
        return x1 / x2

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'df1', 'df2')

    @property
    def mean(self):
        m = self.df2 / (self.df2 - 2)
        return np.where(self.df2 > 2, m, np.full(m.shape, float('nan')))

    @property
    def variance(self):
        d1, d2 = self.df1, self.df2
        v = (2 * d2 ** 2 * (d1 + d2 - 2)
             / (d1 * (d2 - 2) ** 2 * (d2 - 4)))
        return np.where(d2 > 4, v, np.full(v.shape, float('nan')))
