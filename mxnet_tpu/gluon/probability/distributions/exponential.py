"""Exponential distribution (reference
``python/mxnet/gluon/probability/distributions/exponential.py`` —
parameterized by *scale* = 1/rate)."""

from .... import numpy as np
from .exp_family import ExponentialFamily
from .constraint import Positive, NonNegative
from .utils import as_array, sample_n_shape_converter

__all__ = ['Exponential']


class Exponential(ExponentialFamily):
    has_grad = True
    support = NonNegative()
    arg_constraints = {'scale': Positive()}

    def __init__(self, scale=1.0, F=None, validate_args=None):
        self.scale = as_array(scale)
        super().__init__(F=F, event_dim=0, validate_args=validate_args)

    def _batch_shape(self):
        return self.scale.shape

    def log_prob(self, value):
        if self._validate_args:
            self._validate_samples(value)
        return -np.log(self.scale) - value / self.scale

    def sample(self, size=None):
        shape = size if size is not None else self._batch_shape()
        u = np.random.uniform(0.0, 1.0, shape)
        return -self.scale * np.log1p(-u)

    def sample_n(self, size=None):
        return self.sample(sample_n_shape_converter(size)
                           + self._batch_shape())

    def broadcast_to(self, batch_shape):
        return self._broadcast_args(batch_shape, 'scale')

    def cdf(self, value):
        return -np.expm1(-value / self.scale)

    def icdf(self, value):
        return -self.scale * np.log1p(-value)

    @property
    def mean(self):
        return self.scale

    @property
    def variance(self):
        return self.scale ** 2

    def entropy(self):
        return 1 + np.log(self.scale)

    @property
    def _natural_params(self):
        return (-1 / self.scale,)

    def _log_normalizer(self, x):
        return -np.log(-x)
