"""``gluon.loss`` — loss layers (reference python/mxnet/gluon/loss.py)."""

from .block import HybridBlock
from ..ndarray.ndarray import NDArray
from ..ops.registry import get_op, invoke

__all__ = ['Loss', 'L2Loss', 'L1Loss', 'SigmoidBinaryCrossEntropyLoss',
           'SigmoidBCELoss', 'SoftmaxCrossEntropyLoss', 'SoftmaxCELoss',
           'KLDivLoss', 'CTCLoss', 'HuberLoss', 'HingeLoss',
           'SquaredHingeLoss', 'LogisticLoss', 'TripletLoss', 'PoissonNLLLoss',
           'CosineEmbeddingLoss', 'SDMLLoss']


def _op(name, *args, **kw):
    return invoke(get_op(name), args, kw)


def _apply_weighting(loss, weight=None, sample_weight=None):
    """Reference loss.py:_apply_weighting."""
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(pred, label):
    if isinstance(label, NDArray) and label.shape != pred.shape:
        label = label.reshape(pred.shape)
    return label


class Loss(HybridBlock):
    """Base loss (reference loss.py:Loss)."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def _mean(self, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = _op('square', label - pred)
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return self._mean(loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = _op('abs', label - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """Reference loss.py:SigmoidBinaryCrossEntropyLoss (stable log-sum-exp
    form when from_sigmoid=False)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(pred, label)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = _op('relu', pred) - pred * label + \
                    _op('softplus', -_op('abs', pred))
            else:
                log_weight = 1 + (pos_weight - 1) * label
                loss = pred - pred * label + log_weight * (
                    _op('softplus', -_op('abs', pred)) +
                    _op('relu', -pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(_op('log', pred + eps) * label +
                         _op('log', 1. - pred + eps) * (1. - label))
            else:
                loss = -(_op('log', pred + eps) * label * pos_weight +
                         _op('log', 1. - pred + eps) * (1. - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Reference loss.py:SoftmaxCrossEntropyLoss."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = _op('log_softmax', pred, axis=self._axis)
        if self._sparse_label:
            loss = -_op('pick', pred, label, axis=self._axis, keepdims=False)
        else:
            label = _reshape_like(pred, label)
            loss = -(pred * label).sum(axis=self._axis)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = _op('log_softmax', pred, axis=self._axis)
        loss = label * (_op('log', label + 1e-12) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class CTCLoss(Loss):
    """Reference loss.py:CTCLoss over nn/ctc_loss.cc."""

    def __init__(self, layout='NTC', label_layout='NT', weight=None,
                 **kwargs):
        batch_axis = label_layout.find('N')
        super().__init__(weight, batch_axis, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        if self._layout == 'NTC':
            pred = pred.swapaxes(0, 1)
        if self._batch_axis == 1:
            label = label.swapaxes(0, 1)
        loss = _op('ctc_loss', pred, label, data_lengths=pred_lengths,
                   label_lengths=label_lengths)
        return _apply_weighting(loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = _op('abs', label - pred)
        loss = _op('where', loss > self._rho,
                   loss - 0.5 * self._rho,
                   (0.5 / self._rho) * _op('square', loss))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = _op('relu', self._margin - pred * label)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = _op('square', _op('relu', self._margin - pred * label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format='signed',
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if self._label_format == 'signed':
            label = (label + 1.0) / 2.0
        loss = _op('relu', pred) - pred * label + \
            _op('softplus', -_op('abs', pred))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean(loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(pred, positive)
        negative = _reshape_like(pred, negative)
        loss = (_op('square', positive - pred) -
                _op('square', negative - pred))
        axes = tuple(range(1, loss.ndim))
        loss = _op('relu', loss.sum(axis=axes) + self._margin)
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, target, sample_weight=None, epsilon=1e-08):
        target = _reshape_like(pred, target)
        if self._from_logits:
            loss = _op('exp', pred) - target * pred
        else:
            loss = pred - target * _op('log', pred + epsilon)
        if self._compute_full:
            stirling = target * _op('log', target + 1e-12) - target + \
                0.5 * _op('log', 2 * 3.141592653589793 * target + 1e-12)
            stirling = _op('where', target <= 1, _op('zeros_like', stirling),
                           stirling)
            loss = loss + stirling
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean()


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        input2 = _reshape_like(input1, input2)
        cos = (input1 * input2).sum(axis=-1) / (
            _op('norm', input1, axis=-1) * _op('norm', input2, axis=-1)
            + 1e-12)
        label = label.reshape((-1,))
        loss = _op('where', label == 1, 1.0 - cos,
                   _op('relu', cos - self._margin))
        return _apply_weighting(loss, self._weight, sample_weight)


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (reference loss.py:SDMLLoss)."""

    def __init__(self, smoothing_parameter=0.3, weight=1., batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self.kl_loss = KLDivLoss(from_logits=True)
        self.smoothing_parameter = smoothing_parameter

    def forward(self, x1, x2):
        import numpy as _np
        from ..ndarray.ndarray import array as _array
        batch_size = x1.shape[0]
        # pairwise negative L2 distances as logits
        diff = x1.expand_dims(1) - x2.expand_dims(0)
        dist = _op('sqrt', _op('square', diff).sum(axis=-1) + 1e-12)
        logits = -dist
        logp = _op('log_softmax', logits, axis=-1)
        labels = _np.eye(batch_size, dtype=_np.float32)
        labels = labels * (1 - self.smoothing_parameter) + \
            (1 - labels) * self.smoothing_parameter / (batch_size - 1)
        return self.kl_loss(logp, _array(labels))
