"""``gluon.Trainer`` — bridges Parameters ↔ KVStore ↔ Optimizer.

Reference: ``python/mxnet/gluon/trainer.py`` (_init_kvstore:188, step:334,
_allreduce_grads:385, _update:444, save_states:482). Semantics preserved:
``step(batch_size)`` = gradient aggregation (kvstore pushpull across device
replicas / hosts) + per-parameter optimizer update. On TPU the per-key
priority scheduling (priority=-i for comm/compute overlap) is a no-op —
XLA's async collectives already overlap — but the argument is accepted.
"""

from ..kvstore import create as _create_kvstore
from ..kvstore.base import KVStoreBase
from .. import optimizer as opt
from .parameter import Parameter
from ..ndarray.ndarray import NDArray


class _FusedUnsupported(Exception):
    """Optimizer could not be traced into the fused update executable."""


_FUSED_SENTINEL = object()


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore
                 ='device', compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict,)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError('params must be a dict/list of Parameters')
        self._params = []
        # keyed by id(param): structural names are re-derived by
        # collect_params() calls and can change under the trainer
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(f'invalid parameter {param}')
            self._param2idx[id(param)] = i
            self._params.append(param)
        self._compression_params = compression_params
        self._contexts = self._check_contexts()
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_params = {
            'kvstore': kvstore, 'update_on_kvstore': update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = []
        self._reset_kvstore()

    # ----------------------------------------------------------------- setup
    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx() if param._data is not None or \
                param._deferred_init is not None else None
            if ctx is None:
                continue
            assert contexts is None or contexts == ctx, (
                f'All Parameters must be initialized on the same set of '
                f'contexts, but Parameter {param.name} is on {ctx} while '
                f'previous ones are on {contexts}.')
            contexts = ctx
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                'optimizer_params must be None if optimizer is an instance'
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._states = {}
        self._fused_cache = {}

    def _reset_kvstore(self):
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = list(self._params)

    def _init_kvstore(self):
        """Reference trainer.py:188 — decides kvstore type +
        update_on_kvstore. Here: multi-worker → dist_tpu_sync allreduce
        (never server-side updates: there are no servers)."""
        config = self._kvstore_params
        kv = config['kvstore']
        if kv is None or kv == '' or not self._contexts:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            self._kvstore = kv if isinstance(kv, KVStoreBase) else \
                _create_kvstore(kv)
            if self._compression_params:
                self._kvstore.set_gradient_compression(
                    self._compression_params)
            self._update_on_kvstore = bool(config['update_on_kvstore']) \
                if config['update_on_kvstore'] is not None else False
            if self._update_on_kvstore:
                if any(p._grad_stype == 'row_sparse' for p in self._params):
                    import warnings
                    warnings.warn(
                        'update_on_kvstore=True densifies row_sparse '
                        'gradients: lazy row-wise update semantics '
                        '(no wd/momentum on untouched rows) are lost. '
                        'Use update_on_kvstore=False to keep the sparse '
                        'path.', UserWarning, stacklevel=3)
                self._kvstore.set_optimizer(self._optimizer)
        self._kv_initialized = True

    def _init_params(self):
        """Broadcast initial params across workers (reference
        trainer.py:_init_params)."""
        params_to_init = []
        for param in self._params_to_init:
            if param._deferred_init is not None and param._data is None:
                params_to_init.append(param)
            elif self._kvstore is not None and param._data is not None:
                idx = self._param2idx[id(param)]
                vals = param.list_data()
                self._kvstore.broadcast(idx, vals[0], vals)
        self._params_to_init = params_to_init

    # ------------------------------------------------------------ properties
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ------------------------------------------------------------------ step
    def step(self, batch_size, ignore_stale_grad=False):
        """Reference trainer.py:334."""
        rescale_grad = self._scale / batch_size
        self._check_and_rescale_grad(rescale_grad)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _check_and_rescale_grad(self, scale):
        if self._update_on_kvstore and self._kv_initialized and \
                self._kvstore is not None:
            if self._optimizer.rescale_grad != scale:
                raise UserWarning(
                    'Possible change in the `batch_size` from previous '
                    '`step` detected.')
        self._optimizer.rescale_grad = scale

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        self._allreduce_grads()

    def _allreduce_grads(self):
        """Reference trainer.py:385 — pushpull with priority −i.

        All dense params go through ONE ``fused_pushpull`` call: the
        kvstore coalesces them into fusion buffers and issues a handful
        of async collectives in priority order (the comm/compute overlap
        the reference's per-key priority machinery bought), instead of
        hundreds of per-key dispatches."""
        if self._kvstore is None:
            return
        entries = []
        for i, param in enumerate(self._params):
            if param.grad_req == 'null':
                continue
            if param._grad_stype == 'row_sparse':
                # keep row-sparse grads out of the dense allreduce: the
                # kvstore merge would densify the O(table) gradient —
                # exactly what the sparse path exists to avoid. The
                # local lazy update handles them (reference: sparse
                # params take the push/row_sparse_pull route).
                if getattr(self._kvstore, 'num_workers', 1) > 1 and \
                        not getattr(self, '_warned_sparse_dist', False):
                    import warnings
                    warnings.warn(
                        'row_sparse gradients are applied rank-locally '
                        'under a distributed kvstore (no sparse '
                        'allreduce); replicate embeddings or use '
                        'dist_async for server-side sparse updates.',
                        UserWarning)
                    self._warned_sparse_dist = True
                continue
            grads = param.list_grad()
            if grads:
                entries.append((i, param, grads))
        if not entries:
            return
        if hasattr(self._kvstore, 'fused_pushpull'):
            self._kvstore.fused_pushpull(
                [i for i, _, _ in entries],
                [g for _, _, g in entries],
                outs=[p.list_data() for _, p, _ in entries]
                if self._update_on_kvstore else None,
                priorities=[-i for i, _, _ in entries])
            return
        for i, param, grads in entries:
            if self._update_on_kvstore:
                # server-side update: fresh weights land in the param
                # arrays directly (reference trainer.py:385 out=data)
                self._kvstore.pushpull(i, grads, out=param.list_data(),
                                       priority=-i)
            else:
                self._kvstore.pushpull(i, grads, priority=-i)

    def _update(self, ignore_stale_grad=False):
        """Reference trainer.py:444 — run optimizer per device replica.

        All parameter updates execute as ONE jitted call (the role of the
        reference's fused multi-tensor kernels, optimizer_op.cc
        multi_sgd/preloaded_multi_*): per-param eager dispatch of hundreds
        of tiny update ops would dominate step time on TPU. Falls back to
        the per-param loop if fused tracing fails for a custom optimizer.
        """
        if self._update_on_kvstore:
            return  # server-side update already applied by pushpull
        if getattr(self, '_amp_skip_update', False):
            # amp.unscale detected a gradient overflow: skip this update
            # entirely (no wd/momentum mutation on zeroed grads)
            self._amp_skip_update = False
            return
        live = []
        sparse_live = []
        for i, param in enumerate(self._params):
            if param.grad_req == 'null' or param._data is None:
                continue
            if i not in self._states:
                self._states[i] = self._zero1_place(
                    param, self._optimizer.create_state_multi_precision(
                        i, param.data()))
            if param._grad_stype == 'row_sparse':
                sparse_live.append((i, param))
            else:
                live.append((i, param))
        if sparse_live:
            from ..ndarray import sparse as _sp
            opt = self._optimizer
            wants_rows = getattr(opt, 'lazy_update', False) or \
                opt._sparse_rowwise
            for i, param in sparse_live:
                # row_sparse grads (Embedding(sparse_grad=True)) take the
                # per-param sparse path: the optimizer updates only the
                # rows present in the gradient (reference sgd lazy_update
                # / sparse.adagrad_update). The dense tape grad is
                # compressed here — the nnz discovery is the cast_storage
                # step the reference runs inside the sparse backward
                # kernel. A non-lazy optimizer would densify right back,
                # so only compress when the row-wise path will be taken.
                datas = param.list_data()
                g = param.list_grad()[0]
                if wants_rows and not isinstance(g, _sp.BaseSparseNDArray):
                    g = _sp.row_sparse_array(g)
                self._optimizer.update_multi_precision(
                    i, datas[0], g, self._states[i])
                for d in datas[1:]:
                    d._rebind(datas[0]._data)
        if not live:
            return
        try:
            self._fused_update(live)
        except _FusedUnsupported:
            for i, param in live:
                datas = param.list_data()
                grads = param.list_grad()
                self._optimizer.update_multi_precision(
                    i, datas[0], grads[0], self._states[i])
                for d in datas[1:]:
                    d._rebind(datas[0]._data)
                self._restore_placement(param)

    # ------------------------------------------------------- sharded slots
    def _zero1_place(self, param, state):
        """Place freshly created optimizer slots on the active
        ``mx.sharding`` mesh: the parameter's own layout plus the data
        axis on the first still-replicated divisible dim (ZeRO-1 — the
        GSPMD expression of kvstore/tpu.py ``_zero1_update``'s owner
        plan, where each data-parallel rank holds and updates only its
        slice of the slots). No-op outside a sharding context."""
        from .. import sharding as _sharding
        ctx = _sharding.current()
        if ctx is None:
            return state
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        pspec = getattr(param, '_sharding_spec', None)
        if pspec is None or getattr(param, '_sharding_mesh', None) \
                != ctx.mesh:
            # param never compiled under this mesh: treat as replicated
            pspec = P()

        def place(nd):
            if not isinstance(nd, NDArray) or nd.shape is None:
                return nd
            spec = ctx.zero1_spec(pspec, nd.shape) \
                if nd.shape == param.shape else P()
            nd._rebind(jax.device_put(
                nd._data, NamedSharding(ctx.mesh, spec)))
            return nd

        if isinstance(state, NDArray):
            return place(state)
        if isinstance(state, (list, tuple)):
            return type(state)(place(e) for e in state)
        return state

    def _mesh_place(self, live, ctx):
        """Commit every fused-update operand to the active mesh.

        The operands can arrive on mixed committed device sets: the
        first-ever forward runs eagerly for shape inference and leaves
        params/grads on one device while ``_zero1_place`` already
        committed the fresh slots to the mesh — and conversely a
        trainer warmed outside the context carries single-device slots
        next to mesh-sharded params. jax rejects mixed committed sets
        in one jitted call, so lift stragglers to the param's recorded
        layout (replicated when the graph has not compiled under this
        mesh yet) and rebind in place; the next sharded compile
        re-places params per the rules regardless."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def on_mesh(raw):
            sh = getattr(raw, 'sharding', None)
            return sh is not None and \
                len(sh.device_set) == ctx.n_devices

        for i, p in live:
            sp = getattr(p, '_sharding_spec', None)
            if sp is None or getattr(p, '_sharding_mesh', None) \
                    != ctx.mesh:
                sp = P()
            sh = NamedSharding(ctx.mesh, sp)
            for nd in (p.list_data()[0], p.list_grad()[0]):
                if not on_mesh(nd._data):
                    nd._rebind(jax.device_put(nd._data, sh))
            st = self._states.get(i)
            leaves = [st] if isinstance(st, NDArray) else \
                [e for e in (st or ()) if isinstance(e, NDArray)]
            for e in leaves:
                if not on_mesh(e._data) and e.shape is not None:
                    spec = ctx.zero1_spec(sp, e.shape) \
                        if e.shape == p.shape else P()
                    e._rebind(jax.device_put(
                        e._data, NamedSharding(ctx.mesh, spec)))

    def _restore_placement(self, param):
        """Eager-update fallback: put the rebound weight back on its
        recorded mesh layout (the fused path constrains this inside the
        jitted update instead)."""
        from .. import sharding as _sharding
        ctx = _sharding.current()
        sp = getattr(param, '_sharding_spec', None)
        if ctx is None or sp is None or \
                getattr(param, '_sharding_mesh', None) != ctx.mesh:
            return
        import jax
        from jax.sharding import NamedSharding
        sh = NamedSharding(ctx.mesh, sp)
        for nd in param.list_data():
            if nd._data.sharding != sh:
                nd._rebind(jax.device_put(nd._data, sh))

    # -------------------------------------------------------- fused update
    def _fused_update(self, live):
        import numpy as _onp
        import jax
        import jax.numpy as jnp
        from .. import _tape

        opt = self._optimizer

        def flat_state(s):
            if s is None:
                return []
            if isinstance(s, NDArray):
                return [s._data]
            return [e._data for e in s if isinstance(e, NDArray)]

        from .. import sharding as _sharding
        _ctx = _sharding.current()
        if _ctx is not None:
            self._mesh_place(live, _ctx)

        praws = [p.list_data()[0]._data for _, p in live]
        graws = [p.list_grad()[0]._data for _, p in live]
        sraws = [flat_state(self._states[i]) for i, _ in live]

        # placements join the key under a mesh: the step after the first
        # sharded compile re-places params per the rules, and the fused
        # fn's baked w_shard/s_shard constraints must be rebuilt for the
        # new layouts
        place_key = tuple(str(getattr(r, 'sharding', None))
                          for r in praws) if _ctx is not None else None
        key = (id(opt), opt.rescale_grad, opt.clip_gradient,
               tuple((r.shape, str(r.dtype)) for r in praws),
               _ctx.fingerprint() if _ctx is not None else None,
               place_key)
        fn = self._fused_cache.get(key)
        if fn is None:
            state_templates = [self._states[i] for i, _ in live]
            # under a mesh context, pin the updated weights and slots to
            # the layouts the compiled forward / ZeRO-1 plan expect:
            # GSPMD would otherwise let a replicated param inherit its
            # gradient's data-parallel sharding and break the pjit
            # entry's declared in_shardings on the next step
            w_shard = [None] * len(live)
            s_shard = [None] * len(live)
            if _ctx is not None:
                from jax.sharding import NamedSharding
                for j, (i, p) in enumerate(live):
                    sp = getattr(p, '_sharding_spec', None)
                    if sp is not None and \
                            getattr(p, '_sharding_mesh', None) == _ctx.mesh:
                        w_shard[j] = NamedSharding(_ctx.mesh, sp)
                    s_shard[j] = [
                        e._data.sharding for e in
                        (self._states[i] if isinstance(
                            self._states[i], (list, tuple))
                         else [self._states[i]])
                        if isinstance(e, NDArray)] or None

            # under a mesh the update math must stay partitionable by
            # GSPMD (ZeRO-1 owned tiles, FSDP shards): an opaque
            # pallas_call would force a gather, so the fused optimizer
            # ops take their XLA path there (same fused region, same
            # numbers) and the Pallas path stays a single-chip win
            import contextlib as _contextlib
            from ..ops.pallas import fused_optimizer as _fused_opt
            _pallas_gate = (_fused_opt.pallas_disabled if _ctx is not None
                            else _contextlib.nullcontext)

            def fused(praws_, graws_, sraws_, lrs_, wds_, ts_):
                prev = _tape.set_recording(False)
                _gate = _pallas_gate()
                _gate.__enter__()
                try:
                    new_ws, new_ss = [], []
                    for j, (w, g) in enumerate(zip(praws_, graws_)):
                        tmpl = state_templates[j]
                        if tmpl is None:
                            st = None
                        elif isinstance(tmpl, NDArray):
                            st = NDArray(sraws_[j][0])
                        else:
                            it = iter(sraws_[j])
                            st = type(tmpl)(
                                NDArray(next(it)) if isinstance(e, NDArray)
                                else e for e in tmpl)
                        nw, ns = opt.step(w, g, st, lrs_[j], wds_[j],
                                          ts_[j])
                        # keep the stored weight dtype stable across
                        # steps (bf16-cast nets: math promotes to f32,
                        # the parameter itself must stay bf16)
                        if nw.dtype != w.dtype:
                            nw = nw.astype(w.dtype)
                        if w_shard[j] is not None:
                            nw = jax.lax.with_sharding_constraint(
                                nw, w_shard[j])
                        new_ws.append(nw)
                        if ns is None:
                            ns_list = []
                        elif isinstance(ns, tuple):
                            ns_list = list(ns)
                        else:
                            ns_list = [ns]
                        if s_shard[j]:
                            ns_list = [
                                jax.lax.with_sharding_constraint(e, sh)
                                if sh is not None and hasattr(e, 'shape')
                                else e
                                for e, sh in zip(ns_list, s_shard[j])]
                        new_ss.append(ns_list)
                    return new_ws, new_ss
                finally:
                    _gate.__exit__(None, None, None)
                    _tape.set_recording(prev)

            n = len(live)
            zeros = (jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.float32),
                     jnp.zeros(n, jnp.int32))
            try:
                fn = jax.jit(fused)
                # trace-check BEFORE advancing update counts so a failed
                # optimizer falls back without double-counting
                jax.eval_shape(fn, praws, graws, sraws, *zeros)
            except Exception as e:
                self._fused_cache[key] = _FUSED_SENTINEL
                raise _FusedUnsupported(str(e))
            self._fused_cache[key] = fn
        elif fn is _FUSED_SENTINEL:
            raise _FusedUnsupported('previously failed')

        for i, _ in live:
            opt._update_count(i)
        # constant hyperparameter vectors are cached device-side: three
        # fresh host->device uploads per step are pure dispatch latency
        # on a tunnel-attached TPU
        lr_vals = tuple(opt._get_lr(i) for i, _ in live)
        wd_vals = tuple(opt._get_wd(i) for i, _ in live)
        cached = getattr(self, '_hyper_cache', None)
        if cached is not None and cached[0] == (lr_vals, wd_vals):
            lrs, wds = cached[1], cached[2]
        else:
            lrs = jnp.asarray(lr_vals, jnp.float32)
            wds = jnp.asarray(wd_vals, jnp.float32)
            self._hyper_cache = ((lr_vals, wd_vals), lrs, wds)
        t_vals = tuple(opt._index_update_count[i] for i, _ in live)
        tc = getattr(self, '_t_cache', None)
        if tc is not None and tc[0] == t_vals:
            ts = tc[1]
        elif tc is not None and tc[0] == tuple(t - 1 for t in t_vals):
            ts = tc[1] + 1              # uniform advance: one device add
            self._t_cache = (t_vals, ts)
        else:
            ts = jnp.asarray(t_vals, jnp.int32)
            self._t_cache = (t_vals, ts)
        new_ws, new_ss = fn(praws, graws, sraws, lrs, wds, ts)
        for (i, param), nw, ns in zip(live, new_ws, new_ss):
            datas = param.list_data()
            datas[0]._rebind(nw)
            for d in datas[1:]:
                d._rebind(nw)
            st = self._states[i]
            if st is None:
                continue
            if isinstance(st, NDArray):
                st._rebind(ns[0])
            else:
                k = 0
                for e in st:
                    if isinstance(e, NDArray):
                        e._rebind(ns[k])
                        k += 1

    def update(self, batch_size, ignore_stale_grad=False):
        """Manual update path (reference trainer.py:update)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not self._update_on_kvstore, \
            'update() cannot be called when update_on_kvstore is set'
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    # ------------------------------------------------------------ save / load
    def state_dict(self):
        """Full trainer state as host data (picklable, checkpointable).

        Beyond the optimizer slot states this captures everything the
        update *schedule* depends on: the global update counter, the
        per-index update counts (adam's bias-correction ``t``, per-param
        lr/wd schedules) and the lr-scheduler's mutable attributes —
        omitting any of them makes a restored trainer's next step drift
        from the uninterrupted run.
        """
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        sd = {
            'states': {i: _state_to_host(s)
                       for i, s in self._states.items()},
            'num_update': int(self._optimizer.num_update),
            'index_update_count': {
                int(i): int(c) for i, c in
                self._optimizer._index_update_count.items()},
        }
        sch = getattr(self._optimizer, 'lr_scheduler', None)
        if sch is not None:
            import copy
            sd['lr_scheduler'] = copy.deepcopy(sch.__dict__)
        return sd

    def load_state_dict(self, sd):
        """Restore state captured by :meth:`state_dict` — the next
        ``step`` is bit-identical to the uninterrupted trainer's."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._states = {int(i): _state_from_host(s)
                        for i, s in sd['states'].items()}
        self._optimizer.num_update = int(sd['num_update'])
        self._optimizer._index_update_count = {
            int(i): int(c)
            for i, c in sd.get('index_update_count', {}).items()}
        sch = getattr(self._optimizer, 'lr_scheduler', None)
        if sch is not None and 'lr_scheduler' in sd:
            sch.__dict__.update(sd['lr_scheduler'])
        # drop device-side caches keyed on the old counters/hypers
        self._t_cache = None
        self._hyper_cache = None

    def save_states(self, fname):
        """Reference trainer.py:482 (pickled updater states)."""
        import pickle
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            # optimizer state lives in the kvstore updater in this mode
            # (reference trainer.py:482 warns it's rank-local)
            self._kvstore.save_optimizer_states(fname, dump_optimizer=False)
            return
        with open(fname, 'wb') as f:
            pickle.dump({'version': 2, **self.state_dict()}, f)

    def load_states(self, fname):
        """Reference trainer.py:511."""
        import pickle
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, 'rb') as f:
            payload = pickle.load(f)
        if isinstance(payload, dict):
            self.load_state_dict(payload)
            return
        # legacy format: (states, num_update) tuple — no schedule state
        states, num_update = payload
        self._states = {i: _state_from_host(s) for i, s in states.items()}
        self._optimizer.num_update = num_update
        self._t_cache = None
        self._hyper_cache = None


def _state_to_host(state):
    import numpy as _np
    if state is None:
        return None
    if isinstance(state, NDArray):
        return state.asnumpy()
    if isinstance(state, (list, tuple)):
        return tuple(_state_to_host(s) for s in state)
    return state


def _state_from_host(state):
    import numpy as _np
    from ..ndarray.ndarray import array
    if state is None:
        return None
    if isinstance(state, _np.ndarray):
        return array(state)
    if isinstance(state, tuple):
        return tuple(_state_from_host(s) for s in state)
    return state
