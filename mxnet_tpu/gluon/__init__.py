"""``mx.gluon`` — the imperative modeling API.

Reference: ``python/mxnet/gluon/`` (Block/HybridBlock/Parameter/Trainer +
nn/rnn layers, data, loss, metric, model_zoo). The API surface ports
~verbatim (it has no C++ dependency beyond CachedOp — SURVEY §7 table);
the capture/compile machinery underneath is jax.jit (see block.py).
"""

from .block import Block, HybridBlock, SymbolBlock
from .parameter import Constant, DeferredInitializationError, Parameter
from .trainer import Trainer
from . import nn
from . import loss
from . import data
from . import utils
from . import rnn
from . import model_zoo
from . import contrib
from . import probability
from .. import metric  # gluon.metric is the reference's home for metrics

ParameterDict = dict
