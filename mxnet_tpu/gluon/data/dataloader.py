"""DataLoader (reference python/mxnet/gluon/data/dataloader.py).

The reference forks worker processes that rebuild NDArrays over shared
memory (dataloader.py:67-133, CPUSharedStorageManager). Here workers
exchange plain numpy arrays (pickle over pipes) and the final device_put
happens in the consumer — XLA stages the host→TPU copy asynchronously, which
plays the role of pin_memory+copy streams. num_workers=0 is the
synchronous path; num_workers>0 uses a multiprocessing pool with the
dataset inherited by fork (zero-copy for mmap'd sources like RecordIO).
"""

import multiprocessing
import numpy as _np

from ...ndarray.ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler


def default_batchify_fn(data):
    """Reference dataloader.py:default_batchify_fn."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return NDArray(jnp.stack([d._data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = _np.asarray(data)
    return array(data)


def _as_host(data):
    if isinstance(data, NDArray):
        return data.asnumpy()
    if isinstance(data, (list, tuple)):
        return type(data)(_as_host(d) for d in data)
    return data


# per-loader worker state, keyed so several thread-pool loaders in one
# process don't clobber each other (fork pools inherit a one-entry dict)
_worker_state = {}


def _default_worker_batchify(batch):
    if isinstance(batch[0], tuple):
        cols = list(zip(*batch))
        return tuple(_np.asarray([_as_host(c) for c in col]) for col in cols)
    return _np.asarray([_as_host(b) for b in batch])


def _worker_init(key, dataset, batchify_fn):
    _worker_state[key] = (dataset, batchify_fn)


def _worker_fn(key, samples):
    """Fetch + batchify host-side in the worker. A custom batchify_fn
    runs here too (it must be picklable for process pools and should
    return host arrays)."""
    dataset, batchify_fn = _worker_state[key]
    batch = [dataset[i] for i in samples]
    if batchify_fn is None:
        return _default_worker_batchify(batch)
    return batchify_fn(batch)


class DataLoader:
    """Reference dataloader.py:DataLoader."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout
        # remembered for resumable(): the checkpointable iterator rebuilds
        # the per-epoch plan itself from (batch_size, shuffle, last_batch)
        self._batch_size = batch_size
        self._shuffle = bool(shuffle)
        self._last_batch = last_batch or 'keep'
        self._resumable_ok = (batch_sampler is None and sampler is None
                              and (last_batch or 'keep') in
                              ('keep', 'discard'))
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError('batch_size must be specified unless '
                                 'batch_sampler is specified')
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError('shuffle must not be specified if sampler '
                                 'is specified')
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or 'keep')
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError('batch_size, shuffle, sampler and last_batch '
                             'must not be specified if batch_sampler is '
                             'specified.')
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._prefetch = max(0, prefetch or 2 * self._num_workers)
        self._pool = None
        self._worker_key = id(self)
        if self._num_workers > 0:
            # workers run the user's batchify_fn (or the host-array default);
            # pass None for the default so unpicklable bound defaults never
            # cross the fork pipe
            worker_batchify = batchify_fn
            if thread_pool:
                from multiprocessing.pool import ThreadPool
                self._pool = ThreadPool(
                    self._num_workers, initializer=_worker_init,
                    initargs=(self._worker_key, dataset, worker_batchify))
            else:
                ctx = multiprocessing.get_context('fork')
                self._pool = ctx.Pool(
                    self._num_workers, initializer=_worker_init,
                    initargs=(self._worker_key, dataset, worker_batchify))

    def __iter__(self):
        if self._pool is None:
            for batch in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch])
            return
        # pipelined pool: keep `prefetch` batches in flight
        results = []
        it = iter(self._batch_sampler)
        try:
            for _ in range(self._prefetch):
                results.append(self._pool.apply_async(
                    _worker_fn, (self._worker_key, next(it))))
        except StopIteration:
            pass
        while results:
            res = results.pop(0)
            try:
                results.append(self._pool.apply_async(
                    _worker_fn, (self._worker_key, next(it))))
            except StopIteration:
                pass
            raw = res.get(self._timeout)
            if isinstance(raw, tuple):
                yield [array(r) for r in raw]
            elif isinstance(raw, _np.ndarray):
                yield array(raw)
            else:
                yield raw          # custom batchify output passes through

    def __len__(self):
        return len(self._batch_sampler)

    def resumable(self, shuffle_seed=0, state=None):
        """Checkpointable iterator over this loader's dataset.

        Returns a :class:`_ResumableIter` — an infinite epoch-rolling
        iterator whose position is a tiny state dict
        ``{'epoch', 'batch_index', 'shuffle_seed'}`` (see
        ``state_dict()`` / ``load_state_dict()``). Shuffle order is a
        pure function of ``(shuffle_seed, epoch)``, so restoring the
        state reproduces the exact batch sequence, and the skip to the
        saved position is index arithmetic — no dataset reads for the
        replayed batches.

        Only the default-sampler configuration is resumable (custom
        ``sampler``/``batch_sampler`` objects hold opaque state;
        ``last_batch='rollover'`` carries leftovers across epochs).
        """
        if not self._resumable_ok:
            raise ValueError(
                'resumable() requires the default sampler configuration '
                "(no custom sampler/batch_sampler, last_batch in "
                "('keep', 'discard'))")
        it = _ResumableIter(self._dataset, self._batch_size,
                            self._shuffle, self._last_batch,
                            self._batchify_fn, shuffle_seed)
        if it.batches_per_epoch() == 0:
            raise ValueError(
                f'resumable() would yield no batches: '
                f'len(dataset)={len(self._dataset)} with '
                f'batch_size={self._batch_size} and '
                f'last_batch={self._last_batch!r}')
        if state is not None:
            it.load_state_dict(state)
        return it

    def __del__(self):
        if self._pool is not None:
            self._pool.terminate()


class _ResumableIter:
    """Infinite batch iterator with an explicit, restorable position.

    The epoch-``e`` batch plan is ``default_rng([seed, e])``'s
    permutation (or ``arange`` unshuffled) chunked by ``batch_size`` —
    derived from nothing but ``(seed, e)``, never from the global numpy
    stream, so data-augmentation RNG and shuffle order cannot perturb
    each other across a resume.
    """

    def __init__(self, dataset, batch_size, shuffle, last_batch,
                 batchify_fn, shuffle_seed):
        self._dataset = dataset
        self._batch_size = int(batch_size)
        self._shuffle = shuffle
        self._last_batch = last_batch
        self._batchify_fn = batchify_fn
        self._seed = int(shuffle_seed)
        self._epoch = 0
        self._batch_index = 0
        self._plan = None          # lazily built per epoch

    # ------------------------------------------------------------- position
    def state_dict(self):
        return {'epoch': self._epoch, 'batch_index': self._batch_index,
                'shuffle_seed': self._seed}

    def load_state_dict(self, state):
        self._seed = int(state['shuffle_seed'])
        self._epoch = int(state['epoch'])
        self._batch_index = int(state['batch_index'])
        self._plan = None
        return self

    # ------------------------------------------------------------- iteration
    def _epoch_plan(self):
        n = len(self._dataset)
        if self._shuffle:
            order = _np.random.default_rng(
                [self._seed, self._epoch]).permutation(n)
        else:
            order = _np.arange(n)
        bs = self._batch_size
        stop = n - n % bs if self._last_batch == 'discard' else n
        return [order[i:i + bs] for i in range(0, stop, bs)]

    def batches_per_epoch(self):
        n = len(self._dataset)
        if self._last_batch == 'discard':
            return n // self._batch_size
        return -(-n // self._batch_size)

    def __iter__(self):
        return self

    def __next__(self):
        if self.batches_per_epoch() == 0:
            raise ValueError(
                f'resumable iterator yields no batches: '
                f'len(dataset)={len(self._dataset)} with '
                f'batch_size={self._batch_size} and '
                f'last_batch={self._last_batch!r}')
        if self._plan is None:
            self._plan = self._epoch_plan()
        while self._batch_index >= len(self._plan):
            self._epoch += 1
            self._batch_index = 0
            self._plan = self._epoch_plan()
        batch = self._plan[self._batch_index]
        self._batch_index += 1
        return self._batchify_fn([self._dataset[int(i)] for i in batch])
