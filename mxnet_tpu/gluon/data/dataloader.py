"""DataLoader (reference python/mxnet/gluon/data/dataloader.py).

The reference forks worker processes that rebuild NDArrays over shared
memory (dataloader.py:67-133, CPUSharedStorageManager). Here workers
exchange plain numpy arrays (pickle over pipes) and the final device_put
happens in the consumer — XLA stages the host→TPU copy asynchronously, which
plays the role of pin_memory+copy streams. num_workers=0 is the
synchronous path; num_workers>0 uses a multiprocessing pool with the
dataset inherited by fork (zero-copy for mmap'd sources like RecordIO).
"""

import multiprocessing
import numpy as _np

from ...ndarray.ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler


def default_batchify_fn(data):
    """Reference dataloader.py:default_batchify_fn."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return NDArray(jnp.stack([d._data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = _np.asarray(data)
    return array(data)


def _as_host(data):
    if isinstance(data, NDArray):
        return data.asnumpy()
    if isinstance(data, (list, tuple)):
        return type(data)(_as_host(d) for d in data)
    return data


# per-loader worker state, keyed so several thread-pool loaders in one
# process don't clobber each other (fork pools inherit a one-entry dict)
_worker_state = {}


def _default_worker_batchify(batch):
    if isinstance(batch[0], tuple):
        cols = list(zip(*batch))
        return tuple(_np.asarray([_as_host(c) for c in col]) for col in cols)
    return _np.asarray([_as_host(b) for b in batch])


def _worker_init(key, dataset, batchify_fn):
    _worker_state[key] = (dataset, batchify_fn)


def _worker_fn(key, samples):
    """Fetch + batchify host-side in the worker. A custom batchify_fn
    runs here too (it must be picklable for process pools and should
    return host arrays)."""
    dataset, batchify_fn = _worker_state[key]
    batch = [dataset[i] for i in samples]
    if batchify_fn is None:
        return _default_worker_batchify(batch)
    return batchify_fn(batch)


class DataLoader:
    """Reference dataloader.py:DataLoader."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError('batch_size must be specified unless '
                                 'batch_sampler is specified')
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError('shuffle must not be specified if sampler '
                                 'is specified')
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or 'keep')
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError('batch_size, shuffle, sampler and last_batch '
                             'must not be specified if batch_sampler is '
                             'specified.')
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._prefetch = max(0, prefetch or 2 * self._num_workers)
        self._pool = None
        self._worker_key = id(self)
        if self._num_workers > 0:
            # workers run the user's batchify_fn (or the host-array default);
            # pass None for the default so unpicklable bound defaults never
            # cross the fork pipe
            worker_batchify = batchify_fn
            if thread_pool:
                from multiprocessing.pool import ThreadPool
                self._pool = ThreadPool(
                    self._num_workers, initializer=_worker_init,
                    initargs=(self._worker_key, dataset, worker_batchify))
            else:
                ctx = multiprocessing.get_context('fork')
                self._pool = ctx.Pool(
                    self._num_workers, initializer=_worker_init,
                    initargs=(self._worker_key, dataset, worker_batchify))

    def __iter__(self):
        if self._pool is None:
            for batch in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch])
            return
        # pipelined pool: keep `prefetch` batches in flight
        results = []
        it = iter(self._batch_sampler)
        try:
            for _ in range(self._prefetch):
                results.append(self._pool.apply_async(
                    _worker_fn, (self._worker_key, next(it))))
        except StopIteration:
            pass
        while results:
            res = results.pop(0)
            try:
                results.append(self._pool.apply_async(
                    _worker_fn, (self._worker_key, next(it))))
            except StopIteration:
                pass
            raw = res.get(self._timeout)
            if isinstance(raw, tuple):
                yield [array(r) for r in raw]
            elif isinstance(raw, _np.ndarray):
                yield array(raw)
            else:
                yield raw          # custom batchify output passes through

    def __len__(self):
        return len(self._batch_sampler)

    def __del__(self):
        if self._pool is not None:
            self._pool.terminate()
