"""Datasets (reference python/mxnet/gluon/data/dataset.py)."""

import os

from ...ndarray.ndarray import NDArray


class Dataset:
    """Reference dataset.py:Dataset."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        from .sampler import FilterSampler
        return _SampledDataset(self, FilterSampler(fn, self))

    def shard(self, num_shards, index):
        """Per-worker shard (reference dataset.py:shard) — the data-parallel
        input split for multi-host training."""
        assert 0 <= index < num_shards
        length = len(self)
        shard_len = length // num_shards
        rest = length % num_shards
        start = shard_len * index + min(index, rest)
        end = start + shard_len + (index < rest)
        from .sampler import IndexSampler
        return _SampledDataset(self, IndexSampler(list(range(start, end))))

    def take(self, count):
        from .sampler import IndexSampler
        count = min(count, len(self))
        return _SampledDataset(self, IndexSampler(list(range(count))))

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _SampledDataset(Dataset):
    def __init__(self, dataset, sampler):
        self._dataset = dataset
        self._indices = list(iter(sampler))

    def __len__(self):
        return len(self._indices)

    def __getitem__(self, idx):
        return self._dataset[self._indices[idx]]


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of arrays (reference dataset.py:ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for data in args:
            assert len(data) == self._length, \
                'All arrays must have the same length'
            if isinstance(data, NDArray) and data.ndim == 1:
                data = data.asnumpy()
            self._data.append(data)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(data[idx] for data in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference dataset.py:RecordFileDataset;
    C++ analog src/io/dataset.cc RecordFileDataset)."""

    def __init__(self, filename):
        self.idx_file = os.path.splitext(filename)[0] + '.idx'
        self.filename = filename
        self._native = None
        if not os.path.exists(self.idx_file):
            # no .idx sidecar: the C++ reader builds the index by scanning
            # (src_native/recordio.cc, ≙ dmlc InputSplit indexing)
            from ... import _native
            if _native.get_lib() is not None:
                self._native = _native.NativeIndexedReader(filename)
        if self._native is None:
            from ...recordio import MXIndexedRecordIO
            self._record = MXIndexedRecordIO(self.idx_file, self.filename,
                                             'r')

    def __getitem__(self, idx):
        if self._native is not None:
            return self._native.read(idx)
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        if self._native is not None:
            return len(self._native)
        return len(self._record.keys)


class _DownloadedDataset(Dataset):
    """Base for MNIST/CIFAR-style datasets (reference
    dataset.py:_DownloadedDataset)."""

    def __init__(self, root, transform=None):
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError
