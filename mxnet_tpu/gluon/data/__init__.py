"""``gluon.data`` (reference python/mxnet/gluon/data/)."""

from .dataset import (ArrayDataset, Dataset, RecordFileDataset,
                      SimpleDataset, _DownloadedDataset)
from .sampler import (BatchSampler, RandomSampler, Sampler,
                      SequentialSampler, FilterSampler, IntervalSampler,
                      SplitSampler)
from .dataloader import DataLoader
from . import vision
