"""Vision transforms as HybridBlocks (reference
python/mxnet/gluon/data/vision/transforms.py).
"""

import numpy as _np

from ...block import Block, HybridBlock
from ...nn.basic_layers import HybridSequential, Sequential
from ....ndarray.ndarray import NDArray, array
from ....ops.registry import get_op, invoke


def _op(name, *args, **kw):
    return invoke(get_op(name), args, kw)


class Compose(Sequential):
    """Reference transforms.py:Compose."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


#: reference transforms HybridCompose — every transform here is traceable,
#: so the hybrid variant is the same class
HybridCompose = Compose


class RandomApply(Sequential):
    """Reference transforms/__init__.py:138 — apply ``transforms`` with
    probability ``p`` (host-side coin flip, like the reference)."""

    def __init__(self, transforms, p=0.5):
        super().__init__()
        for t in (transforms if isinstance(transforms, (list, tuple))
                  else [transforms]):
            self.add(t)          # registered children: init/cast/save see them
        self.p = p

    def forward(self, x, *args):
        import random as _random
        if self.p >= _random.random():
            for t in self._children.values():
                x = t(x)
        return (x,) + args if args else x


HybridRandomApply = RandomApply


class Cast(HybridBlock):
    def __init__(self, dtype='float32'):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference
    transforms.py:ToTensor)."""

    def forward(self, x):
        x = x.astype('float32') / 255.0
        if x.ndim == 3:
            return x.transpose((2, 0, 1))
        return x.transpose((0, 3, 1, 2))


class Normalize(HybridBlock):
    """Channel-wise normalize of CHW input (reference
    transforms.py:Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, dtype=_np.float32).reshape(-1, 1, 1)
        self._std = _np.asarray(std, dtype=_np.float32).reshape(-1, 1, 1)

    def forward(self, x):
        mean = array(self._mean, ctx=x._ctx)
        std = array(self._std, ctx=x._ctx)
        return (x - mean) / std


class Resize(HybridBlock):
    """Reference transforms.py:Resize (HWC input)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else \
            (size, size)
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        from ....image import imresize, resize_short
        if self._keep:
            return resize_short(x, min(self._size), self._interp)
        return imresize(x, self._size[0], self._size[1], self._interp)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else \
            (size, size)
        self._interp = interpolation

    def forward(self, x):
        from ....image import center_crop
        return center_crop(x, self._size, self._interp)[0]


class RandomResizedCrop(Block):
    """Reference transforms.py:RandomResizedCrop."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else \
            (size, size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        from ....image import fixed_crop
        h, w = x.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            aspect = _np.random.uniform(*self._ratio)
            new_w = int(round(_np.sqrt(target_area * aspect)))
            new_h = int(round(_np.sqrt(target_area / aspect)))
            if new_w <= w and new_h <= h:
                x0 = _np.random.randint(0, w - new_w + 1)
                y0 = _np.random.randint(0, h - new_h + 1)
                return fixed_crop(x, x0, y0, new_w, new_h, self._size,
                                  self._interp)
        from ....image import center_crop
        return center_crop(x, self._size, self._interp)[0]


class RandomFlipLeftRight(HybridBlock):
    def forward(self, x):
        if _np.random.rand() < 0.5:
            return _op('flip', x, axis=1 if x.ndim == 3 else 2)
        return x


class RandomFlipTopBottom(HybridBlock):
    def forward(self, x):
        if _np.random.rand() < 0.5:
            return _op('flip', x, axis=0 if x.ndim == 3 else 1)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        f = 1.0 + _np.random.uniform(-self._b, self._b)
        return (x.astype('float32') * f).clip(0, 255)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        f = 1.0 + _np.random.uniform(-self._c, self._c)
        x = x.astype('float32')
        mean = x.mean()
        return ((x - mean) * f + mean).clip(0, 255)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        f = 1.0 + _np.random.uniform(-self._s, self._s)
        x = x.astype('float32')
        gray = x.mean(axis=-1, keepdims=True)
        return (x * f + gray * (1 - f)).clip(0, 255)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))

    def forward(self, x):
        for t in _np.random.permutation(len(self._ts)):
            x = self._ts[t](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA noise (reference transforms.py:RandomLighting)."""

    _eigval = _np.array([55.46, 4.794, 1.148], dtype=_np.float32)
    _eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], dtype=_np.float32)

    def __init__(self, alpha_std=0.05):
        super().__init__()
        self._std = alpha_std

    def forward(self, x):
        alpha = _np.random.normal(0, self._std, 3).astype(_np.float32)
        rgb = (self._eigvec * alpha) @ self._eigval
        return (x.astype('float32') + array(rgb)).clip(0, 255)
