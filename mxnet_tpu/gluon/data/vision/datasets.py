"""Vision datasets (reference python/mxnet/gluon/data/vision/datasets.py).

Download-dependent datasets (MNIST/CIFAR) read from local files when
present (MXNET_HOME/datasets, same layout as the reference); the zero-egress
CI environment uses synthetic fallbacks in tests instead.
"""

import gzip
import os
import pickle
import struct
import tarfile

import numpy as _np

from ....ndarray.ndarray import array
from ..dataset import Dataset, RecordFileDataset, _DownloadedDataset


def _data_home():
    return os.environ.get('MXNET_HOME',
                          os.path.join(os.path.expanduser('~'), '.mxnet'))


class MNIST(_DownloadedDataset):
    """Reference datasets.py:MNIST (idx-format files)."""

    def __init__(self, root=None, train=True, transform=None):
        self._train = train
        root = root or os.path.join(_data_home(), 'datasets', 'mnist')
        self._train_data = ('train-images-idx3-ubyte.gz',)
        self._train_label = ('train-labels-idx1-ubyte.gz',)
        self._test_data = ('t10k-images-idx3-ubyte.gz',)
        self._test_label = ('t10k-labels-idx1-ubyte.gz',)
        super().__init__(root, transform)

    def _read_idx(self, path):
        opener = gzip.open if path.endswith('.gz') else open
        if not os.path.exists(path) and path.endswith('.gz') and \
                os.path.exists(path[:-3]):
            path, opener = path[:-3], open
        with opener(path, 'rb') as f:
            _, _, ndim = struct.unpack('>HBB', f.read(4))
            dims = struct.unpack('>' + 'I' * ndim, f.read(4 * ndim))
            return _np.frombuffer(f.read(), dtype=_np.uint8).reshape(dims)

    def _get_data(self):
        data_file = (self._train_data if self._train else self._test_data)[0]
        label_file = (self._train_label if self._train
                      else self._test_label)[0]
        data = self._read_idx(os.path.join(self._root, data_file))
        label = self._read_idx(os.path.join(self._root, label_file))
        self._data = array(data[..., None])
        self._label = label.astype(_np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root=None, train=True, transform=None):
        root = root or os.path.join(_data_home(), 'datasets', 'fashion-mnist')
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """Reference datasets.py:CIFAR10 (python pickle batches)."""

    def __init__(self, root=None, train=True, transform=None):
        self._train = train
        root = root or os.path.join(_data_home(), 'datasets', 'cifar10')
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, 'rb') as f:
            batch = pickle.load(f, encoding='bytes')
        data = batch[b'data'].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        label = _np.array(batch.get(b'labels', batch.get(b'fine_labels')))
        return data, label

    def _get_data(self):
        base = os.path.join(self._root, 'cifar-10-batches-py')
        if not os.path.isdir(base):
            tar = os.path.join(self._root, 'cifar-10-python.tar.gz')
            if os.path.exists(tar):
                with tarfile.open(tar) as t:
                    t.extractall(self._root)
        files = [f'data_batch_{i}' for i in range(1, 6)] if self._train \
            else ['test_batch']
        datas, labels = [], []
        for fn in files:
            d, l = self._read_batch(os.path.join(base, fn))
            datas.append(d)
            labels.append(l)
        self._data = array(_np.concatenate(datas))
        self._label = _np.concatenate(labels).astype(_np.int32)


class CIFAR100(CIFAR10):
    def __init__(self, root=None, fine_label=False, train=True,
                 transform=None):
        self._fine = fine_label
        root = root or os.path.join(_data_home(), 'datasets', 'cifar100')
        CIFAR10.__init__(self, root, train, transform)

    def _get_data(self):
        base = os.path.join(self._root, 'cifar-100-python')
        files = ['train'] if self._train else ['test']
        datas, labels = [], []
        for fn in files:
            d, l = self._read_batch(os.path.join(base, fn))
            datas.append(d)
            labels.append(l)
        self._data = array(_np.concatenate(datas))
        self._label = _np.concatenate(labels).astype(_np.int32)


class ImageRecordDataset(RecordFileDataset):
    """Images + labels from a RecordIO pack (reference
    datasets.py:ImageRecordDataset; C++ src/io/dataset.cc
    ImageRecordFileDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....recordio import unpack
        from ....image import imdecode
        record = super().__getitem__(idx)
        header, img_bytes = unpack(record)
        img = imdecode(img_bytes, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """class-per-subfolder layout (reference datasets.py:ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = ['.jpg', '.jpeg', '.png', '.bmp']
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from ....image import imread
        img = imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageListDataset(Dataset):
    """Reference datasets.py:ImageListDataset (.lst format)."""

    def __init__(self, root='.', imglist=None, flag=1):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self.items = []
        if isinstance(imglist, str):
            with open(imglist) as f:
                for line in f:
                    parts = line.strip().split('\t')
                    label = float(parts[1]) if len(parts) == 3 else \
                        [float(i) for i in parts[1:-1]]
                    self.items.append((os.path.join(self._root, parts[-1]),
                                       label))
        else:
            for entry in imglist or []:
                self.items.append((os.path.join(self._root, entry[-1]),
                                   entry[0] if len(entry) == 2
                                   else list(entry[:-1])))

    def __getitem__(self, idx):
        from ....image import imread
        img = imread(self.items[idx][0], self._flag)
        return img, self.items[idx][1]

    def __len__(self):
        return len(self.items)
