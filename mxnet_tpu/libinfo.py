"""Version and build-feature information.

TPU-native analog of the reference's ``python/mxnet/libinfo.py`` (version at
libinfo.py:149) and ``src/libinfo.cc`` feature flags. There is no ``libmxnet.so``
to locate: the compute backend is JAX/XLA, so "features" report what the JAX
installation supports instead of CMake build flags.
"""

__version__ = "2.0.0.tpu0"


def find_lib_path():
    """Kept for API compatibility; there is no native core library to load.

    The reference resolves ``libmxnet.so`` here (libinfo.py:25). In the
    TPU-native design the backend is the in-process JAX/XLA runtime, so this
    returns an empty list.
    """
    return []
