"""Bulked (lazy) eager execution — the imperative engine's fast path.

TPU-native re-design of the reference engine's operation bulking
(include/mxnet/engine.h:310 ``StartBulk``/``StopBulk``,
src/imperative/imperative_utils.h:636 ``RunGraph`` bulk segments): the
reference fuses up to ``MXNET_ENGINE_BULK_SIZE`` consecutive engine pushes
into one scheduled unit to amortize per-op dispatch. Here the per-op cost
being amortized is an XLA executable launch (and, on the axon dev tunnel, a
2-5 ms RPC), so bulking goes further: consecutive imperative ops are
*recorded* into a segment and compiled into ONE cached XLA program, flushed
at sync points.

How it works
------------
* ``registry.apply_op`` offers each invoke()-dispatched op to
  :func:`try_record`. If bulking is active, the op is appended to the
  thread-local :class:`_Segment` and the caller receives **lazy** NDArrays
  (``NDArray._lazy`` holds a :class:`LazyRef` with the abstract value;
  ``NDArray._data`` materializes on touch).
* The segment keeps a **trie** keyed by (op name, static-argument key,
  grad-activity, input wiring): a training loop's second iteration walks the
  same trie path and reuses the recorded output avals — no re-abstract-eval,
  no retracing, no per-op device dispatch.
* A **flush** (sync point: ``_data`` touch, ``backward()``, segment-size
  cap, explicit ``engine.bulk`` exit) compiles — once per (trie node, live
  output set) — a jitted replay of the whole segment and executes it as one
  device program. Subsequent identical segments are a dict hit + one call.
* Autograd: per-op tape nodes are *not* created inside a segment. Instead
  the flush populates ONE :class:`_tape.TapeNode` covering the segment,
  whose vjp re-linearizes the jitted replay (rematerialized backward — the
  standard TPU trade of FLOPs for memory/launches). Ops that would not have
  been recorded eagerly (recording off, non-differentiable, no tracked
  input) get ``lax.stop_gradient`` in the replay, reproducing the eager
  tape's gradient-blocking exactly.

Reference: engine.h:310-317 (bulk API), imperative_utils.h:636 (bulked
graph execution), docs faq env_var MXNET_ENGINE_BULK_SIZE.

Correctness guards:
* ops with unhashable static arguments (device arrays baked as constants,
  numpy buffers) fall back to eager dispatch (registry builds no bulk key);
* a trie position whose children keep multiplying (a Python-scalar constant
  that changes every iteration, e.g. a hand-rolled schedule) is marked
  unstable and ops at it run eagerly — one compile cannot be reused, so
  caching would turn into a compile-per-step storm;
* dynamic-output-shape ops raise under abstract evaluation and fall back;
* deferred-compute capture, per-op profiling, ``naive_engine`` and jit
  tracing all bypass bulking (checked by the registry / via tracer inputs).
"""

import os
import threading
import weakref

import jax
from jax import lax

from . import _tape
from .analysis import race as _race
from .analysis.race import guarded_by as _guarded_by

_MAX_SIBLINGS = 16     # distinct static-arg keys per (position, op) before
                       # the position is treated as unstable
_RETRY = 13            # re-admit every Nth attempt while unstable, so a
                       # later loop with STABLE constants can recover
_MAX_TOTAL = 64        # hard cap on keys per (position, op): bounds the
                       # worst-case compile count from a varying constant


class LazyRef:
    """A pending value: output ``key`` of a segment, materialized at flush."""

    __slots__ = ('seg', 'key', 'aval', 'value', '__weakref__')

    def __init__(self, seg, key, aval):
        self.seg = seg
        self.key = key          # (entry_idx, out_idx)
        self.aval = aval        # jax.ShapeDtypeStruct
        self.value = None


class _Entry:
    __slots__ = ('fn', 'in_refs', 'n_out', 'multi', 'stopgrad', 'out_refs')

    def __init__(self, fn, in_refs, n_out, multi, stopgrad):
        self.fn = fn
        self.in_refs = in_refs      # tuple of (0, boundary_idx) | (1, ei, oi)
        self.n_out = n_out
        self.multi = multi
        self.stopgrad = stopgrad
        self.out_refs = []          # weakrefs to LazyRefs


class _TrieNode:
    __slots__ = ('children', 'out_avals', 'multi', 'plans', 'op_counts',
                 'attempts')

    def __init__(self):
        self.children = {}
        self.out_avals = None       # this entry's output avals
        self.multi = False
        self.plans = {}             # out_keys -> _Plan (flush-here plans)
        self.op_counts = {}         # op name -> distinct keys seen here
        self.attempts = {}          # op name -> turned-away attempts


class _Plan:
    __slots__ = ('jfwd', 'fwd_raw', 'replay', 'out_keys', 'vjp_cache')

    def __init__(self, jfwd, fwd_raw, replay, out_keys):
        self.jfwd = jfwd
        self.fwd_raw = fwd_raw      # unjitted: boundary -> output tuple
        self.replay = replay        # unjitted full-env replay, for re-vjp
        self.out_keys = out_keys
        self.vjp_cache = {}         # nonzero-cot index tuple -> jitted vjp


class _SegVjp:
    """Segment-level vjp: recompute-based, jitted, cached per cotangent
    sparsity pattern. ``indexed`` lets the tape skip materializing zero
    cotangents for the (typically many) outputs that received none."""

    __slots__ = ('plan', 'boundary')

    def __init__(self, plan, boundary):
        self.plan = plan
        self.boundary = boundary

    def indexed(self, present):
        idxs = tuple(sorted(present))
        jf = self.plan.vjp_cache.get(idxs)
        if jf is None:
            replay = self.plan.replay
            sel = tuple(self.plan.out_keys[i] for i in idxs)

            def vjp_apply(boundary, cts):
                def f(*b):
                    env = replay(*b)
                    return tuple(env[ei][oi] for ei, oi in sel)
                _, vjp = jax.vjp(f, *boundary)
                return vjp(cts)

            jf = jax.jit(vjp_apply)
            self.plan.vjp_cache[idxs] = jf
        return jf(tuple(self.boundary), tuple(present[i] for i in idxs))

    def __call__(self, cots):
        # full-cotangent fallback (create_graph and other tape paths that
        # pre-build dense cotangent lists)
        if not isinstance(cots, tuple):
            cots = (cots,)
        return self.indexed(dict(enumerate(cots)))


class _Segment:
    def __init__(self, state):
        self.state = state
        self.lock = threading.RLock()
        self._race = None
        if _race.enabled():
            # declared level 'bulk.segment' (analysis/locks.py); every
            # entries/trie mutation must hold self.lock — the Eraser
            # lockset checker verifies it across foreign-thread settles
            self.lock = _race.tracked(self.lock, 'bulk.segment')
            self._race = _race.shared_state('bulk._Segment',
                                            guard=self.lock)
        self.boundary = []          # raw jax arrays
        self.boundary_ids = {}      # (id(raw), id(ag)) -> index
        self.boundary_ags = []      # AGInfo|None per boundary input
        self.entries = []
        self.trie_pos = state.trie
        self.agrefs = []            # ((ei, oi), weakref(AGInfo))
        self.ag_by_key = {}         # (ei, oi) -> weakref(AGInfo) we created
        self.tape_node = None
        self.flushed = False

    # ------------------------------------------------------------- recording
    @_guarded_by('lock')
    def add(self, op, arrays, fn, bulk_key, grad_active):
        """Append one op. Returns list of LazyRefs, or None (caller goes
        eager; segment left consistent)."""
        if self._race is not None:
            self._race.write()
        # Pass 1 — validate before mutating anything: an in-segment lazy
        # value whose NDArray carries an _ag DIFFERENT from the AGInfo this
        # segment attached to that output (detach()+attach_grad alias, a
        # variable rebound via _adopt_lazy) has lineage the segment graph
        # cannot express — the cotangent would be misrouted to the recorded
        # producer. Settle the segment and let the op dispatch eagerly.
        for nd in arrays:
            ref = nd._lazy
            if ref is not None and ref.seg is self and ref.value is None:
                ag = getattr(nd, '_ag', None)
                if ag is not None:
                    w = self.ag_by_key.get(ref.key)
                    if w is None or w() is not ag:
                        self.flush()
                        return None

        in_refs = []
        in_avals = []
        descr = []
        for nd in arrays:
            ref = nd._lazy
            ag = getattr(nd, '_ag', None)
            # Per-EDGE gradient connectivity: in eager dispatch the
            # cotangent for an input only propagates if THAT NDArray
            # carries lineage (_ag) — a detach()ed alias of a segment
            # value or of a tracked boundary array must block gradient
            # on its edge even though the underlying value is shared.
            blocked = grad_active and ag is None
            if ref is not None and ref.seg is self and ref.value is None:
                ei, oi = ref.key
                in_refs.append((1, ei, oi, blocked))
                in_avals.append(ref.aval)
                descr.append((1, ei, oi, blocked))
            else:
                raw = nd._raw if ref is None else ref.value
                # key by (buffer, lineage): two NDArrays sharing one raw
                # buffer but carrying distinct AGInfos (x and
                # x.detach()+attach_grad — the TBPTT idiom) must occupy
                # distinct boundary slots, or their gradients collapse
                # into whichever lineage was recorded first. The raw is
                # simply passed twice as replay args; jax.vjp then yields
                # a separate cotangent per slot, matching the eager
                # tape's per-edge parent links.
                bkey = (id(raw), id(ag))
                bidx = self.boundary_ids.get(bkey)
                if bidx is None:
                    bidx = len(self.boundary)
                    self.boundary.append(raw)
                    self.boundary_ids[bkey] = bidx
                    self.boundary_ags.append(ag)
                in_refs.append((0, bidx, 0, blocked))
                in_avals.append(
                    jax.ShapeDtypeStruct(raw.shape, raw.dtype))
                descr.append((0, bidx, blocked, str(raw.dtype))
                             + tuple(raw.shape))

        key = (op.name, bulk_key, grad_active, tuple(descr))
        node = self.trie_pos
        child = node.children.get(key)
        if child is None:
            cnt = node.op_counts.get(op.name, 0)
            if cnt >= _MAX_SIBLINGS:
                # this op at this position keeps arriving with fresh
                # static arguments (e.g. a Python-scalar schedule):
                # caching would compile per iteration, so go eager —
                # but re-admit every _RETRY-th attempt (a later loop
                # with stable constants then recovers the fast path)
                # up to a hard key cap that bounds total compiles.
                a = node.attempts.get(op.name, 0) + 1
                node.attempts[op.name] = a
                if cnt >= _MAX_TOTAL or a % _RETRY:
                    return None
            node.op_counts[op.name] = cnt + 1
            try:
                out = jax.eval_shape(fn, *in_avals)
            except Exception:
                return None         # dynamic shape / trace-hostile op
            child = _TrieNode()
            child.multi = isinstance(out, (tuple, list))
            outs = list(out) if child.multi else [out]
            child.out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype)
                               for o in outs]
            node.children[key] = child
            self.state.misses += 1
        else:
            self.state.hits += 1

        ei = len(self.entries)
        entry = _Entry(fn, tuple(in_refs), len(child.out_avals),
                       child.multi, not grad_active)
        self.entries.append(entry)
        self.trie_pos = child

        refs = []
        for oi, aval in enumerate(child.out_avals):
            ref = LazyRef(self, (ei, oi), aval)
            entry.out_refs.append(weakref.ref(ref))
            refs.append(ref)
        ags = self._make_ags(refs) if grad_active else [None] * len(refs)
        return refs, child.multi, ags

    def _make_ags(self, refs):
        """Create provisional AGInfos for just-recorded outputs. Called
        under the segment lock (from add), so a concurrent flush cannot
        snapshot agrefs between recording and attachment."""
        if self.tape_node is None:
            self.tape_node = _tape.TapeNode(None, [], [], 0,
                                            'bulk_segment', multi=True)
        ags = []
        for ref in refs:
            ag = _tape.AGInfo(node=self.tape_node, index=0)
            w = weakref.ref(ag)
            self.agrefs.append((ref.key, w))
            self.ag_by_key[ref.key] = w
            ags.append(ag)
        return ags

    # --------------------------------------------------------------- flushing
    def flush(self):
        with self.lock:
            if self.flushed:
                return
            if self._race is not None:
                self._race.write()
            self.flushed = True
            if not self.entries:
                _race.handoff_release(self)
                return
            self.state.flushes += 1

            live_keys = []
            live_refs = []
            for ei, e in enumerate(self.entries):
                for oi, w in enumerate(e.out_refs):
                    ref = w()
                    if ref is not None:
                        live_keys.append((ei, oi))
                        live_refs.append(ref)
            out_keys = tuple(live_keys)

            plan = self.trie_pos.plans.get(out_keys)
            if plan is None:
                replay = _build_replay(self.entries)

                def fwd(*boundary):
                    env = replay(*boundary)
                    return tuple(env[ei][oi] for ei, oi in out_keys)

                plan = _Plan(jax.jit(fwd), fwd, replay, out_keys)
                self.trie_pos.plans[out_keys] = plan
                self.state.compiles += 1

            outs = plan.jfwd(*self.boundary)

            for i, ref in enumerate(live_refs):
                ref.value = outs[i]
                ref.seg = None

            if self.tape_node is not None:
                pos = {k: i for i, k in enumerate(out_keys)}
                node = self.tape_node
                node.fn = plan.fwd_raw
                node.in_vals = list(self.boundary)
                node.parents = list(self.boundary_ags)
                node.n_out = len(out_keys)
                node.out_avals = [r.aval for r in live_refs]
                node.vjp_fn = _SegVjp(plan, tuple(self.boundary))
                for key, agw in self.agrefs:
                    ag = agw()
                    if ag is not None and key in pos:
                        ag.index = pos[key]
            # release recording state (tape node keeps what it needs)
            self.entries = []
            self.agrefs = []
            self.ag_by_key = {}
            # happens-before edge: values are published; the recording
            # thread's next access to them is a handoff, not a race
            _race.handoff_release(self)


def _build_replay(entries):
    entries = tuple(entries)

    def replay(*boundary):
        env = []
        for e in entries:
            ins = []
            for r in e.in_refs:
                v = boundary[r[1]] if r[0] == 0 else env[r[1]][r[2]]
                if r[3]:                   # detached/untracked edge
                    v = lax.stop_gradient(v)
                ins.append(v)
            outs = e.fn(*ins)
            outs = list(outs) if isinstance(outs, (tuple, list)) \
                else [outs]
            if e.stopgrad:
                outs = [lax.stop_gradient(o) for o in outs]
            env.append(outs)
        return env

    return replay


# ------------------------------------------------------------------- state
class _State(threading.local):
    def __init__(self):
        self.segment = None
        self.trie = _TrieNode()
        self.size_override = None   # set by force(size=...) for this thread
        self.force_depth = 0
        self.disabled_depth = 0
        self.hits = 0
        self.misses = 0
        self.flushes = 0
        self.compiles = 0


_st = _State()
_env_default = None
# Process-wide defaults (engine.set_bulk_size documents itself as the
# process default, matching the reference's MXNET_ENGINE_BULK_SIZE): the
# enabled switch and segment-size cap are module globals read by every
# thread; the force/disable depths and size_override remain thread-local
# scope overrides.
_enabled = None                 # None = resolve from env/backend
_size = int(os.environ.get('MXNET_ENGINE_BULK_SIZE', 4096))


def _default_enabled():
    """Default: on for accelerator backends (where per-op launch overhead
    dominates), off for CPU (tests / debugging keep strict per-op eager)."""
    global _env_default
    if _env_default is None:
        env = os.environ.get('MXNET_ENGINE_BULK', 'auto')
        if env == '0':
            _env_default = False
        elif env == '1':
            _env_default = True
        else:
            try:
                _env_default = jax.default_backend() != 'cpu'
            except Exception:
                _env_default = False
    return _env_default


def active():
    if _st.disabled_depth:
        return False
    if _st.force_depth:
        return True
    if _enabled is not None:
        return _enabled
    return _default_enabled()


def set_enabled(flag):
    """Explicit process-wide on/off switch (flushes the calling thread's
    pending segment; other threads' segments flush at their own sync
    points)."""
    global _enabled
    flush_current()
    _enabled = flag


def set_size(n):
    """Process-wide default segment-size cap."""
    global _size
    _size = n


def current_size():
    return _st.size_override if _st.size_override is not None else _size


def stats():
    return {'hits': _st.hits, 'misses': _st.misses,
            'flushes': _st.flushes, 'compiles': _st.compiles}


def reset():
    """Drop the segment trie and all cached plans (flushes first)."""
    flush_current()
    _st.trie = _TrieNode()


class force:
    """Context manager: force bulking on (engine.bulk) or off
    (naive_engine / profiling scopes)."""

    def __init__(self, on, size=None):
        self.on = on
        self.size = size
        self.prev_override = None

    def __enter__(self):
        if self.on:
            _st.force_depth += 1
            if self.size:
                self.prev_override = _st.size_override
                _st.size_override = self.size
        else:
            flush_current()
            _st.disabled_depth += 1
        return self

    def __exit__(self, *exc):
        if self.on:
            _st.force_depth -= 1
            if self.size:
                _st.size_override = self.prev_override
            flush_current()
        else:
            _st.disabled_depth -= 1
        return False


def _current():
    seg = _st.segment
    if seg is not None and seg.flushed:
        _st.segment = None
        seg = None
    return seg


def flush_current():
    seg = _current()
    if seg is not None:
        seg.flush()
        _st.segment = None


def materialize(ref):
    if ref.value is None and ref.seg is not None:
        seg = ref.seg
        seg.flush()
        _race.handoff_acquire(seg)


# ------------------------------------------------------------ dispatch hook
def try_record(op, arrays, fn, bulk_key, grad_active):
    """Offer an op to the bulking engine. Returns ``(refs, multi, ags)``
    — the output LazyRefs (caller wraps them, assigns the provisional
    AGInfos, then calls cap_check) — or None (caller dispatches
    eagerly). AGInfo creation happens inside the segment lock so a
    concurrent flush can never miss them."""
    if not active():
        return None
    for nd in arrays:
        ref = nd._lazy
        if ref is None:
            raw = nd._raw
            if raw is None or isinstance(raw, jax.core.Tracer):
                return None
        elif ref.value is None and ref.seg is not None \
                and ref.seg is not _st.segment:
            # lazy value from a foreign (e.g. other-thread) segment:
            # settle it before taking our own lock (avoids lock nesting)
            fseg = ref.seg
            fseg.flush()
            _race.handoff_acquire(fseg)
    while True:
        seg = _current()
        if seg is None:
            seg = _Segment(_st)
            _st.segment = seg
        with seg.lock:
            if seg.flushed:
                # another thread flushed this segment between _current()
                # and the lock; recording into it would orphan the
                # outputs — start a fresh segment
                _st.segment = None
                continue
            return seg.add(op, arrays, fn, bulk_key, grad_active)


def cap_check():
    """Flush if the current segment hit the bulk-size cap. Called by the
    dispatcher after outputs (and their AGInfos) are fully wired."""
    seg = _current()
    if seg is not None and len(seg.entries) >= current_size():
        seg.flush()
        _st.segment = None
