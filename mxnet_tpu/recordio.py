"""RecordIO file format (reference python/mxnet/recordio.py:36,215 +
dmlc-core RecordIO).

Binary-compatible with the reference format so datasets packed by the
reference's ``tools/im2rec`` load here unchanged:

* each record: [kMagic:u32][lrec:u32][data (padded to 4B)]
  where lrec's upper 3 bits are a continuation flag and lower 29 the length;
* ``IRHeader`` packed struct (flag, label, id, id2) for image records.

The pure-Python reader is the portable path; a C++ indexer/reader
(src_native/) accelerates bulk scans in later rounds.
"""

import ctypes
import numbers
import os
import struct

import numpy as _np

_kMagic = 0xced7230a
_IR_FORMAT = 'IfQQ'
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class IRHeader:
    """Image-record header (reference recordio.py:343 IRHeader)."""

    __slots__ = ('flag', 'label', 'id', 'id2')

    def __init__(self, flag, label, id, id2):  # noqa: A002
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2


def pack(header, s):
    """Pack a header + payload into a record string
    (reference recordio.py:pack)."""
    label = header.label
    if isinstance(label, numbers.Number):
        hdr = struct.pack(_IR_FORMAT, 0, float(label), header.id, header.id2)
        return hdr + s
    label = _np.asarray(label, dtype=_np.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    """Reference recordio.py:unpack."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = _np.frombuffer(s[:flag * 4], dtype=_np.float32)
        s = s[flag * 4:]
    header = IRHeader(flag, label, id_, id2)
    return header, s


def unpack_img(s, iscolor=-1):
    header, s = unpack(s)
    img = _decode_img(s, iscolor)
    return header, img


def pack_img(header, img, quality=95, img_fmt='.jpg'):
    import cv2
    if img_fmt.lower() in ('.jpg', '.jpeg'):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    else:
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    assert ret, 'failed to encode image'
    return pack(header, buf.tobytes())


def _decode_img(s, iscolor=-1):
    try:
        import cv2
        return cv2.imdecode(_np.frombuffer(s, dtype=_np.uint8), iscolor)
    except ImportError:
        from PIL import Image
        import io
        return _np.asarray(Image.open(io.BytesIO(s)))


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference recordio.py:36)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.record = None
        self.open()

    def open(self):
        if self.flag == 'w':
            self.record = open(self.uri, 'wb')
            self.writable = True
        elif self.flag == 'r':
            self.record = open(self.uri, 'rb')
            self.writable = False
        else:
            raise ValueError('Invalid flag %s' % self.flag)
        self.pid = os.getpid()

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d['record'] = None
        return d

    def __setstate__(self, d):
        self.__dict__ = d
        self.open()

    def _check_pid(self, allow_reset=False):
        if self.pid != os.getpid():
            if allow_reset:
                self.reset()
            else:
                raise RuntimeError('forked process must reset MXRecordIO')

    def close(self):
        if self.record is not None and not self.record.closed:
            self.record.close()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        self._check_pid()
        length = len(buf)
        self.record.write(struct.pack('<II', _kMagic, length & 0x1fffffff))
        self.record.write(buf)
        pad = (4 - length % 4) % 4
        if pad:
            self.record.write(b'\x00' * pad)

    def read(self):
        assert not self.writable
        self._check_pid(allow_reset=True)
        hdr = self.record.read(8)
        if len(hdr) < 8:
            return None
        magic, lrec = struct.unpack('<II', hdr)
        assert magic == _kMagic, 'invalid record magic'
        length = lrec & 0x1fffffff
        buf = self.record.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.record.read(pad)
        return buf

    def tell(self):
        return self.record.tell()

    def seek(self, pos):
        assert not self.writable
        self._check_pid(allow_reset=True)
        self.record.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO with .idx file (reference recordio.py:215)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split('\t')
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        elif self.writable:
            self.fidx = open(self.idx_path, 'w')

    def close(self):
        super().close()
        if self.fidx is not None and not self.fidx.closed:
            self.fidx.close()

    def __getstate__(self):
        d = super().__getstate__()
        d['fidx'] = None
        return d

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f'{key}\t{pos}\n')
        self.idx[key] = pos
        self.keys.append(key)
