"""Checkpoint helpers (reference python/mxnet/model.py + the NDArray
Save/Load binary format, src/ndarray/ndarray.cc:1697,1820).

Format: ``.npz``-based NDArray map (named tensors) — a portable stand-in for
the reference's magic+version binary map. Gluon's
``save_parameters/load_parameters`` route through these. A
tensorstore/orbax-backed *sharded* checkpoint lives in
mxnet_tpu/parallel/checkpoint.py for the distributed path.
"""

import numpy as _np

from .ndarray.ndarray import NDArray, array

_MAGIC_KEY = '__mxnet_tpu_format__'


def save_ndarray_map(fname, data):
    """mx.nd.save (reference ndarray.cc:1697 NDArray::Save)."""
    if isinstance(data, NDArray):
        data = {'0': data}
    elif isinstance(data, (list, tuple)):
        data = {str(i): v for i, v in enumerate(data)}
    arrays = {k: v.asnumpy() if isinstance(v, NDArray) else _np.asarray(v)
              for k, v in data.items()}
    arrays[_MAGIC_KEY] = _np.array([2, 0])  # format version
    # write through a handle: bare np.savez APPENDS '.npz' to any path
    # not already ending in it, silently saving to a different file
    # than the caller named (reference NDArray::Save writes fname as-is)
    with open(fname, 'wb') as f:
        _np.savez(f, **arrays)


def load_ndarray_map(fname, ctx=None):
    """mx.nd.load (reference ndarray.cc:1820 NDArray::Load)."""
    with _np.load(fname, allow_pickle=False) as z:
        out = {k: array(z[k], ctx=ctx) for k in z.files if k != _MAGIC_KEY}
    keys = list(out)
    if keys and all(k.isdigit() for k in keys):
        return [out[str(i)] for i in range(len(keys))]
    return out


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Reference model.py:save_checkpoint — params-%04d file pair."""
    data = {}
    for k, v in (arg_params or {}).items():
        data[f'arg:{k}'] = v
    for k, v in (aux_params or {}).items():
        data[f'aux:{k}'] = v
    save_ndarray_map(f'{prefix}-{epoch:04d}.params.npz', data)
    if symbol is not None and hasattr(symbol, 'save'):
        symbol.save(f'{prefix}-symbol.json')


def load_checkpoint(prefix, epoch):
    """Reference model.py:load_checkpoint."""
    data = load_ndarray_map(f'{prefix}-{epoch:04d}.params.npz')
    arg_params, aux_params = {}, {}
    for k, v in data.items():
        if k.startswith('arg:'):
            arg_params[k[4:]] = v
        elif k.startswith('aux:'):
            aux_params[k[4:]] = v
    return None, arg_params, aux_params
